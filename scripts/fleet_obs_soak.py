"""Fleet observability soak: the ISSUE 14 acceptance artifact generator.

Stands up a TRACED fleet (router + 2 replica subprocesses) under client
load with a chaos schedule that forces ≥1 retry (an error burst) and ≥1
hedge (a response stall longer than ``hedge_ms``), then proves the
cross-process observability layer end to end:

- every process's span stream merges (``obs/merge.py``) into ONE
  Perfetto-loadable timeline where the hedged request's single trace_id
  crosses the process boundary — committed as ``docs/obs/fleet_trace.json``;
- one fleet ``/metrics`` scrape carries ``ddlpc_fleet_*`` rollups
  (aggregated from every replica + the router) AND the SLO error-budget /
  burn-rate gauges;
- the router's ``router.jsonl`` (now carrying ``kind="slo"`` records) and
  every span stream lint clean against the flat-record schema;
- tracing overhead on the serve request path stays inside PR 6's ≤2% bar
  on an alternating traced/untraced A/B.

Usage:
    python scripts/fleet_obs_soak.py --out docs/obs/fleet_obs_soak.json \
        --trace-out docs/obs/fleet_trace.json
"""

from __future__ import annotations

import argparse
import io
import json
import os
import re
import shutil
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


_CHAOS_LINE = re.compile(r"^\[chaos\] (\w+)")


def _chaos_fired(sup) -> set:
    out = set()
    for rp in sup.replicas:
        try:
            with open(rp.log_path) as f:
                for line in f:
                    m = _CHAOS_LINE.match(line.strip())
                    if m:
                        out.add(m.group(1))
        except OSError:
            pass
    return out


def _http(host, port, method, path, body=None, headers=None, timeout=30):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def lint_stream(path: str) -> int:
    from check_metrics_schema import lint_file

    if not os.path.exists(path):
        return 0
    return len(lint_file(path))


def _median(vals):
    s = sorted(vals)
    mid = len(s) // 2
    return s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0


def measure_overhead(base_dir: str, epochs_each: int = 8) -> dict:
    """PR 6's alternating A/B, faithfully reproduced: two Trainers on the
    same tiny synthetic config differing ONLY in ``trace``, epochs
    interleaved A/B/A/B, per-arm MEDIAN step time (docs/obs/overhead.json
    methodology — this PR touches the tracer's record hot path, so the
    bar is re-measured on the same shape it was set on).  Request-level
    serve A/Bs proved unusable on this host: ~25 ms CPU-steal windows
    every ~100 ms (documented at the PR 11 fleet arm) swamp a ~0.2 ms/
    request span cost with ±6% round-to-round swings.  A span unit-cost
    microbench rides along so the serve-path cost is still stated:
    spans/request × unit cost."""
    from ddlpc_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        TrainConfig,
    )
    from ddlpc_tpu.obs.tracing import Tracer
    from ddlpc_tpu.train.trainer import Trainer

    def cfg(trace: bool, workdir: str) -> ExperimentConfig:
        return ExperimentConfig(
            model=ModelConfig(
                features=(8, 16), bottleneck_features=16, num_classes=4
            ),
            data=DataConfig(
                dataset="synthetic", image_size=(32, 32), synthetic_len=128,
                test_split=8, num_classes=4,
            ),
            train=TrainConfig(
                epochs=1, micro_batch_size=2, sync_period=2,
                dump_images_per_epoch=0, checkpoint_every_epochs=0,
                trace=trace,
            ),
            workdir=workdir,
        )

    trainers = {
        "untraced": Trainer(cfg(False, os.path.join(base_dir, "ov_off"))),
        "traced": Trainer(cfg(True, os.path.join(base_dir, "ov_on"))),
    }
    steps = 128 // (2 * 2)
    epoch_ms = {"untraced": [], "traced": []}
    try:
        for arm in trainers:
            trainers[arm].train_epoch(0)  # compile warmup, unmeasured
        order = list(trainers.items())
        for e in range(epochs_each):
            for arm, tr in (order if e % 2 == 0 else order[::-1]):
                t0 = time.perf_counter()
                tr.train_epoch(e + 1)
                epoch_ms[arm].append(
                    (time.perf_counter() - t0) / steps * 1e3
                )
    finally:
        for tr in trainers.values():
            tr.close()

    # span unit cost (the serve-path per-request cost is spans/request ×
    # this; a traced request carries ~8 spans)
    unit = Tracer(
        enabled=True, service="bench",
        jsonl_path=os.path.join(base_dir, "span_unit.jsonl"),
    )
    t0 = time.perf_counter()
    n_spans = 20000
    for _ in range(n_spans):
        with unit.span("s", a=1):
            pass
    span_us = (time.perf_counter() - t0) / n_spans * 1e6
    unit.close()

    med_off = _median(epoch_ms["untraced"])
    med_on = _median(epoch_ms["traced"])
    return {
        "methodology": "alternating Trainer.train_epoch A/B, median of "
                       f"{epochs_each} epochs/arm x {steps} steps "
                       "(docs/obs/overhead.json shape)",
        "step_ms_trace_off": round(med_off, 3),
        "step_ms_trace_on": round(med_on, 3),
        "overhead_pct": round((med_on - med_off) / med_off * 100.0, 2),
        "span_enabled_jsonl_us": round(span_us, 1),
    }


def run_soak(args) -> dict:
    import numpy as np

    from serve_bench import make_tiny_run
    from ddlpc_tpu.config import FleetConfig
    from ddlpc_tpu.obs import merge
    from ddlpc_tpu.obs.aggregate import TelemetryAggregator
    from ddlpc_tpu.obs.tracing import Tracer
    from ddlpc_tpu.serve.fleet import ReplicaSupervisor, make_fleet_server
    from ddlpc_tpu.serve.router import FleetRouter
    from ddlpc_tpu.train.observability import MetricsLogger

    t_start = time.time()
    base = args.workdir
    shutil.rmtree(base, ignore_errors=True)
    workdir = os.path.join(base, "run")
    make_tiny_run(workdir, seed=0, step=1)

    # Tracing-overhead A/B FIRST, while this process is quiet — after the
    # fleet teardown the host is still digesting subprocess exit + page
    # cache churn, which inflates both arms and the noise floor.
    overhead = measure_overhead(base, epochs_each=args.overhead_epochs)

    cfg = FleetConfig(
        workdir=workdir,
        replicas=2,
        max_batch=4,
        queue_limit=64,
        deadline_ms=0.0,
        request_timeout_ms=4000.0,
        retries=2,
        retry_backoff_ms=10.0,
        hedge_ms=300.0,  # the stall answers at hedge pace → a hedge win
        scrape_every_s=0.5,
        warmup_timeout_s=args.warmup_timeout_s,
        metrics_every_s=1.0,
        trace=True,
        aggregate_every_s=0.5,
        aggregate_stale_after_s=10.0,
        # SLO windows sized to a soak, not a quarter: burn rates over
        # seconds so the artifact shows live gauges, not zeros.
        slo_interactive_p99_ms=2000.0,
        slo_batch_p99_ms=10000.0,
        slo_availability=0.99,
        slo_budget_window_s=120.0,
        slo_fast_window_s=15.0,
        slo_fast_burn=10.0,
        slo_slow_window_s=60.0,
        slo_slow_burn=2.0,
    )
    # Chaos on replica 0 only: an error burst (router retries elsewhere)
    # then a 4 s stall (the 300 ms hedge fires and WINS; the stalled
    # original is cancelled as the loser — exactly the timeline the
    # committed trace must show).
    schedule = {(0, 1): "serve_err@12:2;serve_stall@26:4"}

    def env_fn(idx: int, launch: int):
        env = dict(os.environ)
        env.pop("DDLPC_CHAOS", None)
        env.setdefault("JAX_PLATFORMS", "cpu")
        spec = schedule.get((idx, launch))
        if spec:
            env["DDLPC_CHAOS"] = spec
        return env

    fleet_dir = cfg.resolved_fleet_dir()
    os.makedirs(fleet_dir, exist_ok=True)
    logger = MetricsLogger(fleet_dir, basename="router")
    tracer = Tracer(
        enabled=True,
        service="router",
        jsonl_path=os.path.join(fleet_dir, "router_spans.jsonl"),
        chrome_path=os.path.join(fleet_dir, "router_trace.json"),
    )
    router = FleetRouter(cfg, logger=logger, tracer=tracer)
    aggregator = TelemetryAggregator(stale_after_s=cfg.aggregate_stale_after_s)
    aggregator.add_source("router", router.registry.exposition)
    aggregator.start(cfg.aggregate_every_s)
    sup = ReplicaSupervisor(
        cfg, router=router, logger=logger, env_fn=env_fn,
        echo=not args.quiet, aggregator=aggregator,
    )
    ready = sup.start(wait_ready=True)
    if ready < cfg.replicas:
        sup.stop()
        raise RuntimeError(f"only {ready}/{cfg.replicas} replicas ready")
    server = make_fleet_server(
        router, sup, cfg.host, 0, aggregator=aggregator
    )
    server_thread = threading.Thread(target=server.serve_forever, daemon=True)
    server_thread.start()
    port = server.server_address[1]

    rng = np.random.default_rng(0)
    buf = io.BytesIO()
    np.save(buf, rng.uniform(0, 1, (32, 32, 3)).astype(np.float32),
            allow_pickle=False)
    body = buf.getvalue()

    load = {"ok": 0, "errors": 0}

    def one_request() -> None:
        status, _ = _http(
            cfg.host, port, "POST", "/predict", body=body,
            headers={"Content-Type": "application/x-npy"},
        )
        if status >= 500:
            load["errors"] += 1
        else:
            load["ok"] += 1

    # Drive load until both fault reactions are accounted (or timeout):
    # the error burst must cost ≥1 retry, the stall ≥1 hedge.
    deadline = time.time() + args.load_timeout_s
    while time.time() < deadline:
        one_request()
        snap = router.metrics.snapshot(advance=False)
        if snap["retries"] >= 1 and snap["hedges"] >= 1 and load["ok"] >= 40:
            break
        time.sleep(0.05)
    # A few more so the SLO windows hold a healthy tail.
    for _ in range(10):
        one_request()
        time.sleep(0.02)

    router.emit()  # slo record + burn-rate evaluation on the stream
    aggregator.scrape_once()

    # ---- the fleet /metrics scrape (text exposition) ----------------------
    status, scrape = _http(
        cfg.host, port, "GET", "/metrics",
        headers={"Accept": "text/plain"},
    )
    scrape_text = scrape.decode("utf-8", "replace")
    fleet_lines = [
        l for l in scrape_text.splitlines()
        if l.startswith(("ddlpc_fleet_", "ddlpc_slo_"))
    ]
    status_h, health_body = _http(cfg.host, port, "GET", "/healthz")
    health = json.loads(health_body)

    snap = router.metrics.snapshot()
    slo_status = router.slo.status()
    chaos = sorted(_chaos_fired(sup))

    server.shutdown()
    server.server_close()
    sup.stop()
    aggregator.close()
    tracer.close()

    # ---- merge the per-process streams ------------------------------------
    span_files = merge.fleet_span_files(fleet_dir)
    records = merge.read_spans(span_files)
    hedged = [
        r for r in records
        if r.get("name") == "router_attempt" and r.get("reason") == "hedge"
    ]
    hedged_trace = hedged[0].get("trace_id") if hedged else None
    trace_summary = {}
    attribution_row = {}
    if hedged_trace:
        doc = merge.build_timeline(records, trace_id=hedged_trace)
        if args.trace_out:
            merge.write_trace(doc, args.trace_out)
        flows = [e for e in doc["traceEvents"] if e.get("ph") in ("s", "f")]
        trace_summary = {
            "trace_id": hedged_trace,
            "spans": doc["metadata"]["spans"],
            "processes": doc["metadata"]["processes"],
            "flow_events": len(flows),
            "written_to": args.trace_out,
        }
        attribution_row = merge.attribution(records, hedged_trace)

    lint_violations = lint_stream(os.path.join(fleet_dir, "router.jsonl"))
    for p in span_files:
        lint_violations += lint_stream(p)

    report = {
        "schema": 1,
        "host": {"cpus": os.cpu_count()},
        "replicas": cfg.replicas,
        "chaos_schedule": {f"r{i}@launch{l}": s
                           for (i, l), s in schedule.items()},
        "chaos_fired": chaos,
        "load": dict(load),
        "router_metrics": {
            k: snap[k]
            for k in ("requests", "errors_5xx", "attempts", "retries",
                      "hedges", "hedge_wins", "p99_ms")
        },
        "slo": slo_status,
        "fleet_healthz_has_slo": "slo" in health,
        "fleet_metrics_scrape": {
            "status": status,
            "fleet_and_slo_lines": fleet_lines[:60],
            "fleet_line_count": len(fleet_lines),
            "has_fleet_rollup": any(
                'replica="fleet"' in l for l in fleet_lines
            ),
            "has_error_budget": any(
                l.startswith("ddlpc_slo_error_budget_remaining")
                for l in fleet_lines
            ),
        },
        "merged_trace": trace_summary,
        "hedged_request_attribution": attribution_row,
        "span_streams": span_files,
        "schema_lint_violations": lint_violations,
        "tracing_overhead": overhead,
        "wall_s": round(time.time() - t_start, 1),
    }
    report["survived"] = bool(
        load["errors"] == 0
        and snap["retries"] >= 1
        and snap["hedges"] >= 1
        and trace_summary.get("processes", 0) >= 3
        and trace_summary.get("flow_events", 0) >= 2
        and report["fleet_metrics_scrape"]["has_fleet_rollup"]
        and report["fleet_metrics_scrape"]["has_error_budget"]
        and report["fleet_healthz_has_slo"]
        and lint_violations == 0
        and overhead["overhead_pct"] <= 2.0
    )
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default="/tmp/ddlpc_fleet_obs_soak")
    ap.add_argument("--out", default=None, help="write the report JSON here")
    ap.add_argument("--trace-out", default=None,
                    help="write the hedged request's merged trace.json here")
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--warmup-timeout-s", type=float, default=300.0)
    ap.add_argument("--load-timeout-s", type=float, default=120.0)
    ap.add_argument("--overhead-epochs", type=int, default=8,
                    help="alternating A/B epochs per arm")
    args = ap.parse_args(argv)

    report = run_soak(args)
    out = json.dumps(report, indent=2)
    print(out)
    if args.out:
        from ddlpc_tpu.utils.fsio import atomic_write_text

        atomic_write_text(args.out, out + "\n")
    print(
        f"fleet_obs_soak_survived={int(report['survived'])} "
        f"retries={report['router_metrics']['retries']} "
        f"hedges={report['router_metrics']['hedges']} "
        f"trace_processes={report['merged_trace'].get('processes', 0)} "
        f"overhead_pct={report['tracing_overhead']['overhead_pct']}"
    )
    return 0 if report["survived"] else 1


if __name__ == "__main__":
    sys.exit(main())
