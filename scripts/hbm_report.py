"""Per-buffer HBM breakdown for a config's train step, replicated vs ZeRO.

For each ``--layout`` arm this builds the real compiled SPMD train step
for ``--config`` on an ``--devices``-sized mesh, then reports where the
per-device state bytes live: params, optimizer-boundary grads, optimizer
moments, batch stats — computed exactly from every leaf's global shape ×
its committed sharding (``sharding.shard_shape``, backend-independent),
plus whatever aggregate numbers the backend's
``compiled.memory_analysis()`` exposes.  The committed artifact
(docs/sharding/hbm_report.json) is the evidence for the ZeRO ladder's
1/N trajectory (docs/SHARDING.md has the budget math):

- ``zero1``: opt_state ÷ N (params/grads full);
- ``zero2``: opt_state AND the persistent grads ÷ N;
- ``zero3``: params too ÷ N — everything that persists scales 1/N
  (``grads_accum``, the transient backward accumulator, stays full on
  every layout and is reported honestly alongside).

Runs on a virtual CPU mesh by default — buffer layout is decided at
partitioning time, identically on every backend.

Usage:
  python scripts/hbm_report.py [--config configs/vaihingen_unet_tpu_flagship.json]
      [--devices 8] [--micro-batch 4] [--layout zero1 zero2 zero3]
      [--out docs/sharding/hbm_report.json]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from ddlpc_tpu.utils.fsio import atomic_write_json  # noqa: E402


def _leaf_bytes_per_device(tree) -> int:
    # Shape×sharding accounting lives in the package now
    # (ddlpc_tpu/obs/hbm.py) — one implementation for this CLI and the
    # trainer's live ddlpc_hbm_bytes gauges.
    from ddlpc_tpu.obs.hbm import leaf_bytes_per_device

    return leaf_bytes_per_device(tree)


def _memory_analysis(compiled) -> dict:
    """Aggregate backend numbers when available (TPU reports full per-space
    stats; the CPU backend may not implement them — record what exists)."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # Unimplemented on some backends
        return {"available": False, "error": f"{type(e).__name__}: {e}"}
    if ma is None:
        return {"available": False}
    out = {"available": True}
    for field in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        v = getattr(ma, field, None)
        if v is not None:
            out[field] = int(v)
    return out


def run_arm(cfg, shard_update: str, micro_batch: int, sync_period: int) -> dict:
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddlpc_tpu.models import build_model_from_experiment
    from ddlpc_tpu.obs import hbm as obs_hbm
    from ddlpc_tpu.parallel.mesh import make_mesh
    from ddlpc_tpu.parallel.shard_update import StateLayout, resolve_shard_update
    from ddlpc_tpu.parallel.train_step import create_train_state, make_train_step
    from ddlpc_tpu.train.optim import build_optimizer

    cfg = cfg.replace(
        parallel=dataclasses.replace(
            cfg.parallel, data_axis_size=-1, space_axis_size=1,
            shard_update=shard_update,
        ),
        train=dataclasses.replace(
            cfg.train, micro_batch_size=micro_batch, sync_period=sync_period
        ),
    )
    mesh = make_mesh(cfg.parallel)
    n = mesh.shape[cfg.parallel.data_axis_name]
    level = resolve_shard_update(
        shard_update, cfg.compression, n, spatial=False,
        grad_clip_norm=cfg.train.grad_clip_norm,
    )
    model = build_model_from_experiment(cfg)
    tx = build_optimizer(cfg.train)
    h, w = cfg.data.image_size
    state = create_train_state(model, tx, jax.random.key(0), (1, h, w, 3))
    layout = StateLayout(
        "replicated" if level == "off" else level, tx, state, mesh,
        cfg.parallel.data_axis_name,
    )
    state = layout.place(state)
    step = make_train_step(
        model, tx, mesh, cfg.compression, shard_update=level,
        param_avals=layout.param_avals,
    )
    A, B = sync_period, micro_batch * n
    images = jax.ShapeDtypeStruct(
        (A, B, h, w, 3), np.float32,
        sharding=NamedSharding(mesh, P(None, cfg.parallel.data_axis_name)),
    )
    labels = jax.ShapeDtypeStruct(
        (A, B, h, w), np.int32,
        sharding=NamedSharding(mesh, P(None, cfg.parallel.data_axis_name)),
    )
    compiled = step.lower(state, images, labels).compile()
    per_buffer = {
        # params/opt_state read their committed shardings off the placed
        # state; the gradient kinds come from the same accounting the
        # live ddlpc_hbm_bytes gauges publish (obs/hbm.py).
        "params": _leaf_bytes_per_device(state.params),
        "grads": obs_hbm.grads_bytes_per_device(
            layout.param_avals, level, n
        ),
        "grads_accum": obs_hbm.grads_accum_bytes_per_device(
            layout.param_avals
        ),
        "opt_state": _leaf_bytes_per_device(state.opt_state),
        "batch_stats": _leaf_bytes_per_device(state.batch_stats),
        "batch_images": images.dtype.itemsize * A * (B // n) * h * w * 3,
        "batch_labels": labels.dtype.itemsize * A * (B // n) * h * w,
    }
    return {
        "shard_update": level,
        "devices": n,
        "replicated_by_rule_bytes": layout.replicated_by_rule_bytes(),
        "state_bytes_per_device": per_buffer,
        "state_bytes_per_device_total": sum(per_buffer.values()),
        "memory_analysis": _memory_analysis(compiled),
    }


def run_pipeline_arm(
    cfg, n_stages: int, level: str, micro_batch: int, sync_period: int
) -> dict:
    """The staged arm: price every pipeline stage's resident state under
    ``pipe=n_stages`` (ZeRO ``level`` within each stage group) and report
    the MAX stage as the headline — the device that decides whether the
    model fits.  No compile: staged programs are host-driven; pricing is
    the same shape × committed-sharding math as the flat arms, off the
    driver's placed per-stage states."""
    import jax

    from ddlpc_tpu.models import build_model_from_experiment
    from ddlpc_tpu.obs import hbm as obs_hbm
    from ddlpc_tpu.parallel.mesh import make_mesh
    from ddlpc_tpu.parallel.pipeline import make_pipeline_train_step
    from ddlpc_tpu.parallel.train_step import create_train_state
    from ddlpc_tpu.train.optim import build_optimizer

    cfg = cfg.replace(
        parallel=dataclasses.replace(
            cfg.parallel, data_axis_size=-1, space_axis_size=1,
            pipeline_stages=n_stages, shard_update=level,
        ),
        train=dataclasses.replace(
            cfg.train, micro_batch_size=micro_batch, sync_period=sync_period
        ),
    )
    mesh = make_mesh(cfg.parallel)
    n_data = mesh.shape[cfg.parallel.data_axis_name]
    model = build_model_from_experiment(cfg)
    tx = build_optimizer(cfg.train)
    h, w = cfg.data.image_size
    state = create_train_state(model, tx, jax.random.key(0), (1, h, w, 3))
    n_micro = max(sync_period, n_stages)
    drv = make_pipeline_train_step(
        model, tx, mesh, cfg.compression, n_microbatches=n_micro,
        data_axis=cfg.parallel.data_axis_name,
        space_axis=cfg.parallel.space_axis_name,
        pipe_axis=cfg.parallel.pipe_axis_name,
        shard_update=level,
    )
    pstate = drv.init_state(state)
    stage_level = level if n_data > 1 else "off"
    per_stage = obs_hbm.pipeline_stage_hbm_bytes(
        pstate.stages, stage_level, n_data
    )
    B_local = micro_batch  # per-replica microbatch rows on a stage device
    carries = drv.carry_avals((n_data * micro_batch, h, w, 3))
    for s, row in enumerate(per_stage):
        # The GPipe stash: stage 0 keeps M input microbatches, interior
        # stages keep M input carries, the last stage also holds labels.
        if s == 0:
            row["batch_images"] = 4 * n_micro * B_local * h * w * 3
        else:
            row["carry_stash"] = obs_hbm.pipeline_carry_stash_bytes(
                carries[s - 1], n_micro, n_data
            )
        if s == n_stages - 1:
            row["batch_labels"] = 4 * n_micro * B_local * h * w
    headline = max(
        per_stage,
        key=lambda r: r["params"] + r["grads"] + r["opt_state"],
    )
    per_buffer = {
        k: headline.get(k, 0)
        for k in ("params", "grads", "grads_accum", "opt_state",
                  "batch_stats", "batch_images", "batch_labels")
    }
    return {
        "shard_update": stage_level,
        "pipeline_stages": n_stages,
        "n_microbatches": n_micro,
        "devices": n_data * n_stages,
        "per_stage_bytes_per_device": per_stage,
        # Headline = the max stage: the device that must fit.
        "state_bytes_per_device": per_buffer,
        "state_bytes_per_device_total": sum(headline.values()),
        "memory_analysis": {
            "available": False,
            "reason": "staged host-driven programs (no single compiled step)",
        },
    }


_PIPE_ARM = re.compile(r"^pipe(\d+)(?:_(zero[12]))?$")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--config", default="configs/vaihingen_unet_tpu_flagship.json"
    )
    p.add_argument("--devices", type=int, default=8)
    p.add_argument(
        "--micro-batch", type=int, default=4,
        help="per-replica micro-batch for the compiled program (state "
        "buffers are batch-independent; small keeps CPU compiles quick)",
    )
    p.add_argument("--sync-period", type=int, default=2)
    p.add_argument(
        "--layout", nargs="+", default=["zero1", "zero2", "zero3"],
        choices=["zero1", "zero2", "zero3", "pipe2", "pipe4",
                 "pipe2_zero2", "pipe4_zero2"],
        help="layout arms to report next to the replicated baseline "
        "(the 'off' arm always runs): ZeRO levels, and pipeN[_zero2] "
        "staged arms (N pipeline stages, optional ZeRO-2 within each "
        "stage group) whose headline is the max stage's bytes",
    )
    p.add_argument("--out", default="docs/sharding/hbm_report.json")
    args = p.parse_args()

    from ddlpc_tpu.utils.compat import force_cpu_devices

    force_cpu_devices(args.devices)

    from ddlpc_tpu.config import ExperimentConfig

    with open(args.config) as f:
        cfg = ExperimentConfig.from_dict(json.load(f))

    arms = {}
    for arm in ["off"] + list(args.layout):
        m = _PIPE_ARM.match(arm)
        if m:
            arms[arm] = run_pipeline_arm(
                cfg, int(m.group(1)), m.group(2) or "off",
                args.micro_batch, args.sync_period,
            )
        else:
            arms[arm] = run_arm(cfg, arm, args.micro_batch, args.sync_period)
    off = arms["off"]["state_bytes_per_device"]
    reductions = {}
    for name, arm in arms.items():
        if name == "off":
            continue
        b = arm["state_bytes_per_device"]
        reductions[name] = {
            kind: round(off[kind] / max(b[kind], 1), 2)
            for kind in ("params", "grads", "opt_state")
        }
        reductions[name]["state_total"] = round(
            arms["off"]["state_bytes_per_device_total"]
            / max(arm["state_bytes_per_device_total"], 1),
            2,
        )
    report = {
        "config": args.config,
        "devices": args.devices,
        "micro_batch_per_replica": args.micro_batch,
        "arms": arms,
        # Per-layout params/grads/opt_state reduction vs the replicated
        # baseline — the 1/N trajectory the acceptance gauge pins.
        "reduction_x": reductions,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    atomic_write_json(args.out, report)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
