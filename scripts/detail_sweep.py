"""DetailHead capacity/design sweep on the hard task (VERDICT r3 next #3).

Round 3 shipped the only refinement point ever trained (full-res hidden=16,
hard-task mIoU 0.897 at 120 epochs vs the 0.991 matched-budget full-res
anchor) without attempting a sweep.  This script runs the round-4 Pareto
candidates at EXACTLY the r3 extended-budget protocol (micro 8 × sync 4,
lr 1e-3, fp16 codec, 120 epochs, synthetic_hard 512²,
docs/convergence_ab_hard120/) so every row is comparable to the committed
r3 table:

- full-res DetailHead at hidden 32 / 64 (capacity sweep of the r3 design);
- StemGridDetailHead (detail_head_kind='s2d') at hidden 32 / 64 / 128 with
  the grouped train layout — the round-4 fused-head candidates
  (scripts/head_bench.py measures their throughput side).

Writes per-arm JSONL + merged summary into --outdir (default the r3
directory, tags keep arms distinct).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_SCRIPTS_DIR))
sys.path.insert(0, _SCRIPTS_DIR)

from convergence_ab import merge_summary, run_variant  # noqa: E402

ARMS = {
    # r3 design, more capacity.
    "stem4_detail_h32_hard": dict(detail_head=True, detail_head_hidden=32),
    "stem4_detail_h64_hard": dict(detail_head=True, detail_head_hidden=64),
    # Stem-grid refinement (s2d kind) + grouped train layout.
    "stem4_s2dhead_h16_hard": dict(
        detail_head=True, detail_head_kind="s2d", detail_head_hidden=16,
        train_head_layout="grouped",
    ),
    "stem4_s2dhead_h32_hard": dict(
        detail_head=True, detail_head_kind="s2d", detail_head_hidden=32,
        train_head_layout="grouped",
    ),
    "stem4_s2dhead_h64_hard": dict(
        detail_head=True, detail_head_kind="s2d", detail_head_hidden=64,
        train_head_layout="grouped",
    ),
    "stem4_s2dhead_h128_hard": dict(
        detail_head=True, detail_head_kind="s2d", detail_head_hidden=128,
        train_head_layout="grouped",
    ),
}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=120)
    p.add_argument("--outdir", default="docs/convergence_ab_hard120")
    p.add_argument("--only", default="")
    args = p.parse_args()

    tags = [t for t in args.only.split(",") if t] or list(ARMS)
    results = []
    for tag in tags:
        rec = run_variant(
            tag,
            4,
            "float16",
            args.epochs,
            args.outdir,
            dataset="synthetic_hard",
            **ARMS[tag],
        )
        results.append(rec)
        print(json.dumps(rec), flush=True)

    merge_summary(args.outdir, results)


if __name__ == "__main__":
    main()
