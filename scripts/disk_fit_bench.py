"""One REAL `fit()` from disk-loaded imagery on the accelerator.

VERDICT r3 missing #2: every committed training run fed from in-memory
synthetic arrays; the converters were unit-tested on fixtures but no
`fit()` had ever consumed their output through `load_tile_dir` /
`CropDataset`, and the ShardedLoader's host-upload path (the one a pod
uses) had no recorded accelerator run.  This script closes both:

1. Synthesizes ISPRS-geometry fixtures (orthophoto scenes + color-coded
   GT at the benchmark's conventions) and runs the REAL converter
   (`scripts/prepare_isprs.py`) on them → a scene directory of
   `<stem>.png` + `<stem>.npy` pairs.
2. Tiles one scene into a fixed 512² tile directory (`load_tile_dir`
   format) the way the reference's private pre-converted folder was laid
   out (кластер.py:660-674).
3. Runs the flagship architecture's `Trainer.fit()` TWICE from that disk
   data on the default backend (the real TPU under the driver):
   a. crop mode — `CropDataset` + `DihedralAugment` over the converter's
      scene dir, `ShardedLoader` host-upload path (`device_cache=False`);
   b. fixed-tile mode — `load_tile_dir` over the tiled directory, same
      upload path.
   Both record metrics + stage-resolved throughput into
   docs/disk_fit/run.json.

The tiles/s here measures the HOST LINK (this environment tunnels the
device, ~1-2 MB/s effective), not the chip: docs/PERF.md carries the
interpretation next to the device-cache numbers.

Usage: python scripts/disk_fit_bench.py [--epochs 2] [--out docs/disk_fit]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_SCRIPTS_DIR))

import numpy as np


def write_fixtures(root: str, size: int = 1536, n_scenes: int = 3) -> tuple:
    """ISPRS-convention fixtures: top_mosaic_*.png + color-coded GT."""
    import imageio.v2 as imageio

    sys.path.insert(0, _SCRIPTS_DIR)
    from prepare_isprs import ISPRS_COLORS

    from ddlpc_tpu.data.datasets import SyntheticTiles

    tops = os.path.join(root, "top")
    gts = os.path.join(root, "gts")
    os.makedirs(tops), os.makedirs(gts)
    big = SyntheticTiles(
        num_tiles=n_scenes, image_size=(size, size), num_classes=6, seed=7
    )
    for i in range(n_scenes):
        img = (big.images[i] * 255).astype(np.uint8)
        lab = big.labels[i]
        imageio.imwrite(os.path.join(tops, f"top_mosaic_{i:02d}.png"), img)
        imageio.imwrite(
            os.path.join(gts, f"top_mosaic_{i:02d}_label.png"),
            ISPRS_COLORS[lab],
        )
    return tops, gts


def tile_scene_dir(scene_dir: str, out_dir: str, tile: int = 512) -> int:
    """Cut converter-output scenes into a fixed 512² tile dir
    (load_tile_dir format: <stem>.png + <stem>.npy), reference layout."""
    import imageio.v2 as imageio

    from ddlpc_tpu.data.datasets import load_scene_dir

    os.makedirs(out_dir, exist_ok=True)
    n = 0
    for si, (img, lab) in enumerate(load_scene_dir(scene_dir)):
        H, W = lab.shape
        for y in range(0, H - tile + 1, tile):
            for x in range(0, W - tile + 1, tile):
                stem = f"tile_{si}_{y}_{x}"
                imageio.imwrite(
                    os.path.join(out_dir, f"{stem}.png"),
                    (img[y : y + tile, x : x + tile] * 255).astype(np.uint8),
                )
                np.save(
                    os.path.join(out_dir, f"{stem}.npy"),
                    lab[y : y + tile, x : x + tile],
                )
                n += 1
    return n


def run_fit(tag: str, data_kw: dict, epochs: int, workdir: str) -> dict:
    from ddlpc_tpu.config import (
        CompressionConfig,
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ParallelConfig,
        TrainConfig,
    )
    from ddlpc_tpu.train.trainer import Trainer

    cfg = ExperimentConfig(
        # Flagship architecture (s2d×4 + DetailHead, bf16 head).  Batch
        # sized to the reference-scale dataset (micro 32 × sync 4 = one
        # 128-tile super-batch) rather than the device-cache benchmark's
        # B=128, so an epoch is data-defined, not wrap-dominated.
        model=ModelConfig(
            width_divisor=2, num_classes=6, stem="s2d", stem_factor=4,
            detail_head=True, head_dtype="bfloat16",
        ),
        data=DataConfig(num_classes=6, device_cache=False, **data_kw),
        train=TrainConfig(
            epochs=epochs,
            micro_batch_size=32,
            sync_period=4,
            learning_rate=1e-3,
            dump_images_per_epoch=0,
            checkpoint_every_epochs=0,
            eval_every_epochs=epochs,
            stall_timeout_s=900.0,
            stall_action="abort",
        ),
        parallel=ParallelConfig(data_axis_size=1),
        compression=CompressionConfig(mode="float16"),
        workdir=workdir,
    )
    t0 = time.perf_counter()
    trainer = Trainer(cfg, resume=False)
    rec = trainer.fit()
    rec = dict(rec)
    rec["tag"] = tag
    rec["wall_s"] = round(time.perf_counter() - t0, 1)
    rec["train_tiles"] = len(trainer.train_ds)
    return rec


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--outdir", default="docs/disk_fit")
    args = p.parse_args()

    tmp = tempfile.mkdtemp(prefix="diskfit_")
    tops, gts = write_fixtures(tmp)
    scenes = os.path.join(tmp, "scenes")
    # The REAL converter, as a user runs it.
    subprocess.run(
        [
            sys.executable,
            os.path.join(_SCRIPTS_DIR, "prepare_isprs.py"),
            "--images", tops, "--labels", gts, "--out", scenes,
        ],
        check=True,
    )
    tiles = os.path.join(tmp, "tiles")
    n_tiles = tile_scene_dir(scenes, tiles)
    print(f"fixtures ready: 3 scenes -> {n_tiles} fixed tiles", flush=True)

    results = [
        run_fit(
            "crop_augment_scene_dir",
            dict(
                data_dir=scenes,
                dataset="vaihingen",
                image_size=(512, 512),
                crops_per_epoch=128,
                test_split_scenes=1,
                test_split=8,
                augment=True,
            ),
            args.epochs,
            os.path.join(tmp, "run_crop"),
        ),
        run_fit(
            "fixed_tile_dir",
            dict(
                data_dir=tiles,
                dataset="vaihingen",
                image_size=(512, 512),
                test_split=4,
            ),
            args.epochs,
            os.path.join(tmp, "run_tiles"),
        ),
    ]
    for r in results:
        print(json.dumps(r), flush=True)
    os.makedirs(args.outdir, exist_ok=True)
    from ddlpc_tpu.utils.fsio import atomic_write_json

    atomic_write_json(
        os.path.join(args.outdir, "run.json"),
        {
            "note": (
                "Flagship-arch fit() from DISK through the REAL "
                "converter output and the ShardedLoader host-upload "
                "path (device_cache=False) on the default backend.  "
                "tiles_per_s measures the tunneled host link, not the "
                "chip — see docs/PERF.md."
            ),
            "runs": results,
        },
    )
    print("disk fit bench OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
