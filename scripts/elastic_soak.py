"""Elastic-fleet soak: the autoscaler + response cache under a traffic
step, a cache A/B, and a corrupt rolling reload (ISSUE 16 acceptance
evidence — the elastic companion to scripts/fleet_soak.py).

What it proves, end to end, on CPU:

- a **4× traffic step** (2 → 8 closed-loop clients) is absorbed with the
  error budget intact: the autoscaler sees the queue/slot-busy pressure
  and grows the fleet, and the ``kind="autoscale"`` JSONL records show
  replica count following load (scale-ups carrying their triggering
  signal values, then scale-downs after the step ends);
- the **repeated-scene cache arm**: hot-set traffic served from the
  content-addressed response cache has hit-rate > 0 and a measured p99
  strictly below the same traffic forced through ``?cache=bypass``;
- a **rolling reload with an active autoscaler** still aborts
  fleet-wide when a replica corrupts its blob (``reload_corrupt``
  chaos): the blob is quarantined, every updated replica is rolled back
  to the old step, and the rollback path emits the
  ``cache_invalidate reason=reload_rollback`` record — the cache can
  never outlive the weights that produced its entries;
- every JSONL stream (router + autoscale + cache records included)
  lints clean against the flat-record schema.

Usage:
    python scripts/elastic_soak.py --out docs/resilience/elastic_soak.json
    python scripts/elastic_soak.py --quick     # shorter phases

The committed evidence lives at docs/resilience/elastic_soak.json.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import shutil
import struct
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def lint_stream(path: str) -> int:
    """Schema-lint one JSONL stream; returns violation count."""
    from check_metrics_schema import lint_file

    if not os.path.exists(path):
        return 0
    return len(lint_file(path))


def _p99(samples_ms) -> float:
    if not samples_ms:
        return 0.0
    s = sorted(samples_ms)
    return round(s[min(int(0.99 * (len(s) - 1)), len(s) - 1)], 3)


def run_soak(args) -> dict:
    import numpy as np

    from serve_bench import make_tiny_run
    from ddlpc_tpu.config import FleetConfig
    from ddlpc_tpu.serve.autoscale import Autoscaler
    from ddlpc_tpu.serve.fleet import ReplicaSupervisor
    from ddlpc_tpu.serve.router import FleetRouter
    from ddlpc_tpu.train.observability import MetricsLogger

    t_start = time.time()
    base = args.workdir
    shutil.rmtree(base, ignore_errors=True)
    workdir = os.path.join(base, "run")
    make_tiny_run(workdir, seed=0, step=1)

    base_clients = 2
    stepped_clients = 8  # a 4x step — inside the >=2x..8x acceptance band
    phase_s = {
        "baseline": 6.0 if args.quick else 10.0,
        "stepped": 25.0 if args.quick else 35.0,
        "downscale": 35.0 if args.quick else 45.0,
    }
    cache_requests = 150 if args.quick else 300

    cfg = FleetConfig(
        workdir=workdir,
        replicas=2,
        max_batch=4,
        max_wait_ms=2.0,
        queue_limit=256,
        deadline_ms=0.0,
        request_timeout_ms=2000.0,
        retries=3,
        retry_backoff_ms=10.0,
        hedge_ms=0.0,  # a saturating step measures capacity, not tail
        scrape_every_s=1.0,
        warmup_timeout_s=args.warmup_timeout_s,
        crash_loop_limit=3,
        backoff_base_s=0.2,
        backoff_cap_s=2.0,
        metrics_every_s=2.0,
        # SLO objective the "error budget intact" claim is audited
        # against: 98% good requests on a 60 s fast window — a CPU-host
        # soak objective, not the production default.
        slo_availability=0.98,
        slo_fast_window_s=60.0,
        # the elastic subsystem under test:
        autoscale_enabled=True,
        autoscale_min_replicas=2,
        autoscale_max_replicas=4,
        autoscale_interval_s=1.0,
        autoscale_cooldown_s=6.0,
        # Host-shaped thresholds: saturated CPU replicas here show a
        # sustained slot-busy fraction ~0.83 (window-averaged, stable)
        # while the batcher's admission queue stays shallow (mean 0-1.5
        # — max_batch drains it between scrapes), so slot busy is the
        # primary trigger and queue depth the secondary.
        autoscale_queue_depth_high=1.5,
        autoscale_queue_depth_low=0.5,
        autoscale_slot_busy_high=0.70,
        autoscale_slot_busy_low=0.30,
        cache_max_bytes=64 << 20,
    )

    # Replica 1 corrupts its blob on its first /reload → quarantine →
    # fleet-wide abort; replica 0 (already updated by then) rolls back.
    schedule = {(1, 1): "reload_corrupt@1"}

    def env_fn(idx: int, launch: int):
        env = dict(os.environ)
        env.pop("DDLPC_CHAOS", None)
        env.setdefault("JAX_PLATFORMS", "cpu")
        spec = schedule.get((idx, launch))
        if spec:
            env["DDLPC_CHAOS"] = spec
        return env

    fleet_dir = cfg.resolved_fleet_dir()
    os.makedirs(fleet_dir, exist_ok=True)
    logger = MetricsLogger(fleet_dir, basename="router")
    router = FleetRouter(cfg, logger=logger)
    sup = ReplicaSupervisor(
        cfg, router=router, logger=logger, env_fn=env_fn, echo=not args.quiet
    )
    ready = sup.start(wait_ready=True)
    startup_s = round(time.time() - t_start, 1)
    if ready < cfg.replicas:
        sup.stop()
        raise RuntimeError(f"only {ready}/{cfg.replicas} replicas became ready")

    # ---- traffic: hot set of 8 cacheable tiles + unique cold misses -------
    rng = np.random.default_rng(0)

    def tile_body() -> bytes:
        buf = io.BytesIO()
        np.save(buf, rng.uniform(0, 1, (32, 32, 3)).astype(np.float32),
                allow_pickle=False)
        return buf.getvalue()

    hot = [tile_body() for _ in range(8)]
    cold_template = tile_body()
    cold_data_off = len(cold_template) - 32 * 32 * 3 * 4

    stop_load = threading.Event()
    stop_stepped = threading.Event()
    load = {"ok": 0, "errors": []}
    load_lock = threading.Lock()

    def client(i: int, stepped: bool) -> None:
        import random as pyrandom

        r = pyrandom.Random(i)
        seq = 0
        gate = stop_stepped if stepped else stop_load
        while not gate.is_set():
            if r.random() < 0.5:
                body = hot[r.randrange(len(hot))]
            else:
                seq += 1
                cold = bytearray(cold_template)
                struct.pack_into("<ff", cold, cold_data_off,
                                 float(i), float(seq))
                body = bytes(cold)
            status, _, payload = router.dispatch(body)
            with load_lock:
                if status >= 500:
                    load["errors"].append(
                        {"client": i, "status": status,
                         "body": payload[:200].decode("utf-8", "replace")}
                    )
                else:
                    load["ok"] += 1
            if not stepped:
                # Base load is gentle; the STEPPED clients are closed-loop
                # with zero think time — that saturation is the pressure
                # the scale-up thresholds exist for.
                time.sleep(0.005)

    timeline = {"t": [], "clients": [], "replicas": [], "ready": [],
                "hit_rate": [], "phase": [], "queue_depth": [],
                "slot_busy": []}
    phase = {"name": "baseline"}
    n_clients = {"n": base_clients}
    stop_sampler = threading.Event()
    t0 = time.perf_counter()

    autoscaler = Autoscaler(
        cfg, router, sup, logger=logger, registry=router.registry
    )

    def sampler() -> None:
        while not stop_sampler.is_set():
            stats = router.cache.stats()
            sig = autoscaler._signals()
            timeline["t"].append(round(time.perf_counter() - t0, 1))
            timeline["clients"].append(n_clients["n"])
            timeline["replicas"].append(sup.replica_count())
            timeline["ready"].append(sup.ready_count())
            timeline["hit_rate"].append(round(stats["cache_hit_rate"], 4))
            timeline["phase"].append(phase["name"])
            timeline["queue_depth"].append(round(sig["queue_depth"], 2))
            timeline["slot_busy"].append(round(sig["slot_busy"], 3))
            stop_sampler.wait(1.0)

    threading.Thread(target=sampler, daemon=True).start()
    autoscaler.start()

    base_threads = [
        threading.Thread(target=client, args=(i, False), daemon=True)
        for i in range(base_clients)
    ]
    for t in base_threads:
        t.start()

    # ---- phase 1: baseline ------------------------------------------------
    time.sleep(phase_s["baseline"])

    # ---- phase 2: the 4x traffic step — scale-up must follow --------------
    phase["name"] = "stepped"
    stepped_threads = [
        threading.Thread(target=client, args=(i, True), daemon=True)
        for i in range(base_clients, stepped_clients)
    ]
    for t in stepped_threads:
        t.start()
    n_clients["n"] = stepped_clients
    time.sleep(phase_s["stepped"])
    replicas_at_peak = sup.replica_count()

    # ---- phase 3: cache A/B — hits vs ?cache=bypass on the SAME tiles -----
    phase["name"] = "cache_ab"
    for body in hot:  # ensure every hot tile is resident
        router.dispatch(body)
    hit_ms, bypass_ms = [], []
    for k in range(cache_requests):
        ta = time.perf_counter()
        router.dispatch(hot[k % len(hot)])
        hit_ms.append((time.perf_counter() - ta) * 1e3)
    for k in range(cache_requests):
        ta = time.perf_counter()
        router.dispatch(hot[k % len(hot)], query="cache=bypass")
        bypass_ms.append((time.perf_counter() - ta) * 1e3)
    cache_ab = {
        "requests_per_arm": cache_requests,
        "hit_p99_ms": _p99(hit_ms),
        "bypass_p99_ms": _p99(bypass_ms),
        "hit_p50_ms": round(sorted(hit_ms)[len(hit_ms) // 2], 3),
        "bypass_p50_ms": round(sorted(bypass_ms)[len(bypass_ms) // 2], 3),
        "hit_rate_overall": round(
            router.cache.stats()["cache_hit_rate"], 4
        ),
    }

    # ---- phase 4: corrupt rolling reload under the live autoscaler --------
    # Settle barrier: rolling reload only touches LIVE replicas, so a
    # still-warming scale-up would miss both the reload and its
    # rollback and come up on the other step.  Wait until every
    # managed replica is ready before pulling the trigger.
    phase["name"] = "corrupt_reload"
    settle_deadline = time.monotonic() + 120.0
    while time.monotonic() < settle_deadline:
        statuses = router.replica_status()
        if statuses and all(s.get("ready") for s in statuses) and len(
            statuses
        ) >= sup.replica_count():
            break
        time.sleep(0.5)
    make_tiny_run(workdir, seed=1, step=2)
    r_reload = sup.rolling_reload()
    reload_evidence = {
        "ok": r_reload.get("ok"),
        "aborted_on": r_reload.get("aborted_on"),
        "reason": r_reload.get("reason"),
        "rolled_back_to": r_reload.get("rolled_back_to"),
        "rollback_clean": r_reload.get("rollback_clean"),
    }

    # ---- phase 5: step ends — scale-down must follow ----------------------
    phase["name"] = "downscale"
    stop_stepped.set()
    for t in stepped_threads:
        t.join(timeout=30)
    n_clients["n"] = base_clients
    time.sleep(phase_s["downscale"])

    stop_load.set()
    for t in base_threads:
        t.join(timeout=30)
    stop_sampler.set()
    autoscaler.close()
    snap = router.metrics.snapshot()
    cache_stats = router.cache.stats()
    fleet_health = router.healthz()
    sup.stop()

    # ---- audit ------------------------------------------------------------
    jsonl = os.path.join(fleet_dir, "router.jsonl")
    records = []
    if os.path.exists(jsonl):
        with open(jsonl) as f:
            records = [json.loads(ln) for ln in f if ln.strip()]
    autoscale_records = [r for r in records if r.get("kind") == "autoscale"]
    scale_ups = [
        r for r in autoscale_records
        if r["action"] == "scale_up" and r.get("reason") != "below_min"
    ]
    scale_downs = [
        r for r in autoscale_records if r["action"] == "scale_down"
    ]
    invalidations = [
        r for r in records
        if r.get("kind") == "router" and r.get("event") == "cache_invalidate"
    ]
    rollback_invalidations = [
        r for r in invalidations if r.get("reason") == "reload_rollback"
    ]
    lint_violations = lint_stream(jsonl)
    for rp in sup.replicas:
        lint_violations += lint_stream(
            os.path.join(rp.home, "serve_metrics.jsonl")
        )

    total = load["ok"] + len(load["errors"])
    error_fraction = (len(load["errors"]) / total) if total else 1.0
    budget = 1.0 - cfg.slo_availability

    report = {
        "schema": 1,
        "host": {"cpus": os.cpu_count()},
        "quick": bool(args.quick),
        "startup_s": startup_s,
        "step": {
            "clients": f"{base_clients} -> {stepped_clients} (4x)",
            "replicas_start": cfg.replicas,
            "replicas_at_peak": replicas_at_peak,
            "replicas_max_seen": max(timeline["replicas"]),
            "replicas_final": timeline["replicas"][-1],
        },
        "load": {
            "requests_ok": load["ok"],
            "errors_5xx_count": len(load["errors"]),
            "errors_5xx": load["errors"][:10],
            "error_fraction": round(error_fraction, 5),
            "error_budget": budget,
        },
        "cache_ab": cache_ab,
        "cache_final": cache_stats,
        "reload": reload_evidence,
        "autoscale_decisions": [
            {k: r.get(k) for k in
             ("action", "reason", "replicas", "replicas_target", "replica",
              "queue_depth", "slot_busy", "burn_rate")}
            for r in autoscale_records
            if r["action"] in ("scale_up", "scale_down")
        ],
        "cache_invalidations": [
            {"reason": r.get("reason"), "dropped": r.get("dropped")}
            for r in invalidations
        ],
        "timeline": timeline,
        "router_metrics": snap,
        "final_fleet": {
            "ready": fleet_health["ready"],
            "checkpoint_steps": fleet_health["checkpoint_steps"],
        },
        "quarantined_blobs": sorted(
            n for n in os.listdir(os.path.join(workdir, "checkpoints"))
            if n.endswith(".bad")
        ),
        "schema_lint_violations": lint_violations,
        "wall_s": round(time.time() - t_start, 1),
    }

    survived = (
        error_fraction <= budget
        and len(scale_ups) >= 1
        and max(timeline["replicas"]) > cfg.replicas
        and len(scale_downs) >= 1
        and timeline["replicas"][-1] < max(timeline["replicas"])
        and cache_ab["hit_rate_overall"] > 0
        and cache_ab["hit_p99_ms"] < cache_ab["bypass_p99_ms"]
        and reload_evidence["ok"] is False
        and bool(reload_evidence["rollback_clean"])
        and len(rollback_invalidations) >= 1
        and bool(report["quarantined_blobs"])
        and report["final_fleet"]["checkpoint_steps"] == [1]
        and lint_violations == 0
    )
    report["survived"] = bool(survived)
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default="/tmp/ddlpc_elastic_soak")
    ap.add_argument("--out", default=None, help="write the report JSON here")
    ap.add_argument("--quick", action="store_true", help="shorter phases")
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--warmup-timeout-s", type=float, default=300.0)
    args = ap.parse_args(argv)

    report = run_soak(args)
    out = json.dumps(report, indent=2)
    print(out)
    if args.out:
        from ddlpc_tpu.utils.fsio import atomic_write_text

        atomic_write_text(args.out, out + "\n")
    # driver-contract line
    print(
        f"elastic_soak_survived={int(report['survived'])} "
        f"errors_5xx={report['load']['errors_5xx_count']} "
        f"replicas_peak={report['step']['replicas_max_seen']} "
        f"cache_hit_p99_ms={report['cache_ab']['hit_p99_ms']} "
        f"bypass_p99_ms={report['cache_ab']['bypass_p99_ms']}"
    )
    return 0 if report["survived"] else 1


if __name__ == "__main__":
    sys.exit(main())
