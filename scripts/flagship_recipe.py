"""Converge THE flagship recipe at its benchmarked operating point.

VERDICT r2 missing #2 / next #3: three different operating points coexisted
— quality evidence at global batch 32, the shipped config at 64/chip, the
bench headline at 128/chip — and no committed run showed the recipe that
produces the headline throughput also converges.  This script closes that:
it trains the flagship architecture (s2d stem + DetailHead, fp16 codec,
bf16 head) at EXACTLY the bench row's per-chip operating point
(micro_batch 128 × sync_period 4 on one chip) on the non-saturating hard
task, sweeping the learning rate for the 16×-larger batch, and commits the
winning curve.  The shipped config and the bench row then record the same
recipe (configs/vaihingen_unet_tpu_flagship.json).

With 97 train tiles and a 512-tile super-batch, one "epoch" is ONE
full-wrap optimizer step (wrap_fill_factor ~5.3); convergence is therefore
budgeted in optimizer STEPS (--steps), matching how the large-batch regime
is actually reasoned about.  Multi-chip extension: the per-chip recipe is
what the curve validates; 8-chip DP at fixed GLOBAL batch is semantics-
checked by bench.py --scaling (identical loss trajectories), and larger
global batches need their own LR point — stated in docs/HARD_TASK.md, not
assumed.

Usage: python scripts/flagship_recipe.py [--lrs 1e-3,2e-3] [--steps 400]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
# Repo root for ddlpc_tpu, scripts dir for convergence_ab: direct invocation
# gets the latter for free via sys.path[0], but `python -m` / imports from
# elsewhere do not (ADVICE r3).
sys.path.insert(0, os.path.dirname(_SCRIPTS_DIR))
sys.path.insert(0, _SCRIPTS_DIR)

from convergence_ab import merge_summary, run_variant  # noqa: E402  (same directory)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--lrs", default="1e-3,2e-3")
    p.add_argument("--steps", type=int, default=400,
                   help="optimizer steps == epochs at this batch (1 step/epoch)")
    p.add_argument("--micro-batch", type=int, default=128)
    p.add_argument("--sync-period", type=int, default=4)
    p.add_argument("--stem-factor", type=int, default=4)
    p.add_argument("--outdir", default="runs/flagship_recipe")
    p.add_argument("--mode", default="float16",
                   help="codec mode for all arms (codec A/B: none|int8|float16)")
    p.add_argument("--rounding", default="nearest")
    p.add_argument("--head-dtype", default="bfloat16",
                   help="fp32 arm isolates the bf16-head quality cost")
    p.add_argument("--detail-kind", default="fullres",
                   help="detail_head_kind: fullres | s2d (round-4 head)")
    p.add_argument("--detail-hidden", type=int, default=16)
    p.add_argument("--head-layout", default="fullres",
                   help="train_head_layout: fullres | grouped")
    p.add_argument("--tag-suffix", default="",
                   help="extra tag suffix distinguishing arch variants")
    args = p.parse_args()

    results = []
    for lr in [float(s) for s in args.lrs.split(",") if s]:
        tag = f"flagship_b{args.micro_batch}x{args.sync_period}_lr{lr:g}"
        if args.mode != "float16" or args.rounding != "nearest":
            tag += f"_{args.mode}_{args.rounding}"
        if args.head_dtype != "bfloat16":
            tag += f"_head{args.head_dtype}"
        # Arch axes auto-encode into the tag like the codec axes do — two
        # arms must never share a tag (run_variant truncates {tag}.jsonl and
        # the summary merge is by tag, so a collision would overwrite the
        # control arm's committed curve).
        if args.detail_kind != "fullres":
            tag += f"_{args.detail_kind}h{args.detail_hidden}"
        elif args.detail_hidden != 16:
            tag += f"_h{args.detail_hidden}"
        if args.head_layout != "fullres":
            tag += f"_{args.head_layout}"
        tag += args.tag_suffix
        rec = run_variant(
            tag,
            args.stem_factor,
            args.mode,
            epochs=args.steps,
            outdir=args.outdir,
            micro_batch=args.micro_batch,
            sync_period=args.sync_period,
            dataset="synthetic_hard",
            head_dtype=args.head_dtype,
            detail_head=True,
            detail_head_kind=args.detail_kind,
            detail_head_hidden=args.detail_hidden,
            train_head_layout=args.head_layout,
            learning_rate=lr,
            rounding=args.rounding,
        )
        results.append(rec)
        print(json.dumps(rec), flush=True)
    # Merge by tag so codec/head arms don't clobber the LR-sweep rows.
    merge_summary(args.outdir, results)


if __name__ == "__main__":
    main()
