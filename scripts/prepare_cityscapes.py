"""Convert a Cityscapes checkout into this framework's tile-directory format.

Cityscapes ships ``leftImg8bit/<split>/<city>/*_leftImg8bit.png`` images and
``gtFine/<split>/<city>/*_gtFine_labelIds.png`` masks whose values are the
33 raw label ids; training uses the standard 19 "trainId" classes with
everything else void.  This tool walks a split, maps labelIds → trainIds
(void → -1, which the loss/metrics/confusion paths all ignore), optionally
downscales (BASELINE config 5 trains 1024×512 halves of the 2048×1024
frames), and writes ``<stem>.png`` + ``<stem>.npy`` pairs that
``load_tile_dir`` / ``load_scene_dir`` consume directly:

    python scripts/prepare_cityscapes.py --root /data/cityscapes \
        --split train --out /data/cs_train --downscale 2

The reference has no counterpart (its only dataset is a prepared Vaihingen
tile folder); this closes the gap for BASELINE config 5.
"""

from __future__ import annotations

import argparse
import os

import numpy as np

# labelId -> trainId for the standard 19-class Cityscapes benchmark
# (Cordts et al. 2016, the 'trainId' column of the official label table);
# every labelId not listed is void.
_TRAIN_IDS = {
    7: 0,  # road
    8: 1,  # sidewalk
    11: 2,  # building
    12: 3,  # wall
    13: 4,  # fence
    17: 5,  # pole
    19: 6,  # traffic light
    20: 7,  # traffic sign
    21: 8,  # vegetation
    22: 9,  # terrain
    23: 10,  # sky
    24: 11,  # person
    25: 12,  # rider
    26: 13,  # car
    27: 14,  # truck
    28: 15,  # bus
    31: 16,  # train
    32: 17,  # motorcycle
    33: 18,  # bicycle
}
VOID = -1


def labelids_to_trainids(label_ids: np.ndarray) -> np.ndarray:
    """[H, W] raw labelIds → int32 trainIds with void = -1."""
    lut = np.full(256, VOID, np.int32)
    for label_id, train_id in _TRAIN_IDS.items():
        lut[label_id] = train_id
    return lut[label_ids.astype(np.uint8)]


def convert_split(
    root: str, split: str, out_dir: str, downscale: int = 1, limit: int = 0,
    fmt: str = "png",
) -> int:
    from PIL import Image

    img_root = os.path.join(root, "leftImg8bit", split)
    gt_root = os.path.join(root, "gtFine", split)
    if not os.path.isdir(img_root):
        raise FileNotFoundError(f"no such split: {img_root}")
    os.makedirs(out_dir, exist_ok=True)
    n = 0
    for city in sorted(os.listdir(img_root)):
        city_dir = os.path.join(img_root, city)
        if not os.path.isdir(city_dir):
            continue
        for name in sorted(os.listdir(city_dir)):
            if not name.endswith("_leftImg8bit.png"):
                continue
            stem = name[: -len("_leftImg8bit.png")]
            gt_path = os.path.join(gt_root, city, f"{stem}_gtFine_labelIds.png")
            if not os.path.exists(gt_path):
                raise FileNotFoundError(f"missing mask for {stem}: {gt_path}")
            img = Image.open(os.path.join(city_dir, name)).convert("RGB")
            mask = Image.open(gt_path)
            if downscale > 1:
                w, h = img.size
                img = img.resize((w // downscale, h // downscale), Image.BILINEAR)
                # NEAREST for masks: interpolating label ids invents classes.
                mask = mask.resize((w // downscale, h // downscale), Image.NEAREST)
            if fmt == "npy":
                # Array-format tiles: uint8 <stem>_img.npy — decode-free
                # per-tile reads for load_tile_dir(lazy=True) at
                # full-Cityscapes volume (2975 tiles ≈ 20 GB eager).
                np.save(
                    os.path.join(out_dir, f"{stem}_img.npy"),
                    np.ascontiguousarray(np.asarray(img, np.uint8)),
                )
            else:
                img.save(os.path.join(out_dir, f"{stem}.png"))
            np.save(
                os.path.join(out_dir, f"{stem}.npy"),
                labelids_to_trainids(np.asarray(mask)),
            )
            n += 1
            if limit and n >= limit:
                return n
    return n


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--root", required=True, help="Cityscapes checkout root")
    p.add_argument("--split", default="train", choices=["train", "val", "test"])
    p.add_argument("--out", required=True, help="output tile directory")
    p.add_argument("--downscale", type=int, default=2)
    p.add_argument("--limit", type=int, default=0, help="stop after N frames")
    p.add_argument(
        "--format", default="png", choices=["png", "npy"], dest="fmt",
        help="npy writes uint8 <stem>_img.npy tiles for decode-free "
             "load_tile_dir(lazy=True) reads",
    )
    args = p.parse_args()
    n = convert_split(
        args.root, args.split, args.out, args.downscale, args.limit,
        fmt=args.fmt,
    )
    print(f"wrote {n} (image, trainId-mask) pairs to {args.out}")


if __name__ == "__main__":
    main()
