"""CLI: ``python -m ddlpc_tpu.train --config cfg.json --set train.epochs=5``.

The reference has no CLI at all — role and every hyperparameter are
hard-coded globals edited per machine (кластер.py:223-252,685-687).  Here a
run is one JSON config artifact plus dotted-path overrides; the same command
works single-chip, v5e-8, or multi-host (set COORDINATOR_ADDRESS /
NUM_PROCESSES / PROCESS_ID or rely on TPU pod auto-detection).
"""

from __future__ import annotations

import argparse
import ast
import sys

from ddlpc_tpu.config import ExperimentConfig


def apply_override(d: dict, dotted: str, value: str) -> None:
    keys = dotted.split(".")
    cur = d
    for k in keys[:-1]:
        if k not in cur or not isinstance(cur[k], dict):
            raise KeyError(f"unknown config section {dotted!r}")
        cur = cur[k]
    if keys[-1] not in cur:
        raise KeyError(f"unknown config key {dotted!r}")
    try:
        cur[keys[-1]] = ast.literal_eval(value)
    except (ValueError, SyntaxError):
        cur[keys[-1]] = value  # bare string


def parse_config(argv=None) -> tuple[ExperimentConfig, bool]:
    p = argparse.ArgumentParser(
        prog="python -m ddlpc_tpu.train", description=__doc__
    )
    p.add_argument("--config", help="JSON config file (ExperimentConfig.to_json)")
    p.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="dotted override, e.g. train.epochs=5 model.name=unetpp",
    )
    p.add_argument("--workdir", help="run directory (logs/checkpoints/images)")
    p.add_argument(
        "--no-resume", action="store_true", help="ignore existing checkpoints"
    )
    args = p.parse_args(argv)

    if args.config:
        with open(args.config) as f:
            cfg = ExperimentConfig.from_json(f.read())
    else:
        cfg = ExperimentConfig()
    d = cfg.to_dict()
    for item in args.set:
        if "=" not in item:
            p.error(f"--set expects KEY=VALUE, got {item!r}")
        key, value = item.split("=", 1)
        apply_override(d, key, value)
    cfg = ExperimentConfig.from_dict(d)
    if args.workdir:
        cfg = cfg.replace(workdir=args.workdir)
    return cfg, not args.no_resume


def main(argv=None) -> int:
    cfg, resume = parse_config(argv)
    from ddlpc_tpu.resilience.protocol import EXIT_PREEMPTED
    from ddlpc_tpu.train.trainer import Trainer

    trainer = Trainer(cfg, resume=resume)
    record = trainer.fit()
    print({k: round(v, 4) if isinstance(v, float) else v for k, v in record.items()})
    if trainer.preempted:
        # Distinct restartable-clean status (resilience/protocol.py): the
        # supervisor relaunches without backoff and the resume skip-replays
        # to the exact preempted step.
        return EXIT_PREEMPTED
    return 0


if __name__ == "__main__":
    sys.exit(main())
