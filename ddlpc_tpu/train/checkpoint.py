"""Checkpoint/resume — absent from the reference (SURVEY §5: no torch.save
anywhere; a crash loses the run).  Design:

- A checkpoint is one msgpack blob (flax.serialization) of the TrainState
  pytree plus a JSON sidecar (step/epoch/config) — all host arrays; on
  restore the caller re-uploads to the mesh (params are replicated, so a
  plain device_put suffices).
- Writes are atomic and durable (tmp file + fsync + rename + directory
  fsync) and pruned to ``keep`` newest, so neither a process crash mid-write
  nor a power loss after _prune can leave a renamed-but-empty blob as the
  only checkpoint.
- Only process 0 writes (state is replicated across hosts); every process
  can restore from shared storage.
- The blob is compressed with the framework wire codec (utils/wire.py —
  C++ multithreaded deflate when built, zlib fallback), the same codec that
  plays the role of the reference's pickle+mgzip transport (кластер.py:43-69).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

PyTree = Any

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack\.z$")
_META_RE = re.compile(r"^ckpt_(\d+)\.json$")


def _to_host(tree: PyTree) -> PyTree:
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _compress(data: bytes) -> bytes:
    from ddlpc_tpu.utils.wire import compress

    return compress(data)


def _decompress(data: bytes) -> bytes:
    from ddlpc_tpu.utils.wire import decompress

    return decompress(data)


def save_checkpoint(
    ckpt_dir: str,
    state: PyTree,
    step: int,
    metadata: Optional[dict] = None,
    keep: int = 3,
) -> Optional[str]:
    """Write ``state`` as checkpoint ``step``; returns the path (None on
    non-zero processes, which skip the write — state is replicated)."""
    if jax.process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    blob = _compress(serialization.to_bytes(_to_host(state)))
    name = f"ckpt_{step}.msgpack.z"
    # Metadata is renamed into place BEFORE the blob: latest_step() keys on
    # the blob, so a crash between the two renames leaves either a harmless
    # orphan .json or nothing — never a restorable blob with lost metadata.
    meta = dict(metadata or {}, step=step)
    meta_tmp = os.path.join(ckpt_dir, f".meta_{step}.tmp")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(meta_tmp, os.path.join(ckpt_dir, f"ckpt_{step}.json"))
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            # fsync before rename: os.replace alone is atomic against
            # process crashes but not power loss — an un-synced blob could
            # survive the rename empty while _prune already deleted the
            # older checkpoints.
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(ckpt_dir, name))
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    # Persist both renames before pruning the fallback checkpoints.
    dir_fd = os.open(ckpt_dir, os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)
    _prune(ckpt_dir, keep)
    return os.path.join(ckpt_dir, name)


def _steps(ckpt_dir: str) -> list[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _prune(ckpt_dir: str, keep: int) -> None:
    live = _steps(ckpt_dir)
    for step in live[:-keep] if keep > 0 else []:
        for suffix in (".msgpack.z", ".json"):
            path = os.path.join(ckpt_dir, f"ckpt_{step}{suffix}")
            if os.path.exists(path):
                os.unlink(path)
    # Sweep metadata orphaned by a crash between the json and blob renames
    # (save order writes json first) — a .json with no blob is never a
    # restorable step and would otherwise accumulate forever.
    alive = set(live[-keep:]) if keep > 0 else set(live)
    for name in os.listdir(ckpt_dir):
        m = _META_RE.match(name)
        if m and int(m.group(1)) not in alive:
            os.unlink(os.path.join(ckpt_dir, name))


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = _steps(ckpt_dir)
    return steps[-1] if steps else None


def peek_metadata(ckpt_dir: str, step: Optional[int] = None) -> dict:
    """Read a checkpoint's JSON sidecar without touching the blob — for
    callers that need metadata (e.g. input_channels) BEFORE they can build
    the restore target."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    meta_path = os.path.join(ckpt_dir, f"ckpt_{step}.json")
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


def restore_checkpoint(
    ckpt_dir: str, target: PyTree, step: Optional[int] = None
) -> Tuple[PyTree, dict]:
    """Restore (state, metadata).  ``target`` supplies the pytree structure
    (a freshly-initialized TrainState); ``step=None`` takes the newest."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"ckpt_{step}.msgpack.z")
    with open(path, "rb") as f:
        state = serialization.from_bytes(target, _decompress(f.read()))
    meta_path = os.path.join(ckpt_dir, f"ckpt_{step}.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    return state, meta
