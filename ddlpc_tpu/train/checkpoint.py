"""Checkpoint/resume — absent from the reference (SURVEY §5: no torch.save
anywhere; a crash loses the run).  Two on-disk formats, one reader:

- **chunked** (default, ``ckpt_<step>.dwc``): the TrainState's state-dict
  leaves are serialized per-leaf into bounded-size chunks of raw array
  bytes, each chunk deflated independently through the DWZ1 wire codec
  (utils/wire.py — adaptive stored-vs-deflate per chunk, so entropy-dense
  fp32 weights stream at ~memcpy speed while zeroed optimizer slots still
  shrink 100×) and streamed to disk as it compresses.  No whole-state
  bytes copy ever exists: peak extra memory is the in-flight compression
  window, not the checkpoint.  A JSON manifest (leaf paths, dtypes,
  shapes, chunk offsets) rides in a footer; restore inflates every chunk
  straight into its leaf's preallocated buffer.
- **monolithic** (legacy, ``ckpt_<step>.msgpack.z``): one flax msgpack
  blob of the whole tree, wire-compressed.  Still written under
  ``format="monolithic"`` and always restorable — the reader dispatches on
  which file exists, so pre-chunked runs resume bit-identically
  (docs/CHECKPOINTS.md has the compat matrix).

Integrity (format version 2, this PR): every chunk frame carries a CRC32
in the footer manifest and the manifest itself is CRC'd in the footer, so
a flipped bit anywhere in the blob is DETECTED at restore instead of
silently becoming weights.  On corruption the restore dispatcher
quarantines the blob (rename to ``*.bad`` — evidence kept, never counted
as a checkpoint again) and falls back to the next-newest complete
checkpoint; only when nothing restorable remains does it raise.  Version-1
blobs (no CRCs) still restore bit-identically — verification is simply
skipped for them (docs/CHECKPOINTS.md compat matrix).

Shared invariants, identical in both formats:

- Writes are atomic and durable (tmp file + fsync + rename + directory
  fsync) and pruned to ``keep`` newest, so neither a process crash
  mid-write nor a power loss after _prune can leave a renamed-but-empty
  blob as the only checkpoint.  Pruning never removes the newest
  checkpoint whose footer still verifies — if the newest blob on disk is
  corrupt, the one restore would fall back to survives any ``keep``.
- The JSON metadata sidecar is renamed into place BEFORE the blob
  (latest_step keys on the blob, so a crash between the renames leaves a
  harmless orphan .json, never a blob with lost metadata).
- Only process 0 writes (state is replicated across hosts); every process
  can restore from shared storage.

The async layer on top (train/async_checkpoint.py) snapshots the state to
host and hands ``save_snapshot`` to a background thread so the next
epoch's compute overlaps the I/O.
"""

from __future__ import annotations

import json
import os
import re
import struct
import tempfile
import time
import warnings
import zlib
from typing import Any, Iterator, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from ddlpc_tpu.obs import lineage as _lineage
from ddlpc_tpu.resilience.chaos import active as _chaos_active
from ddlpc_tpu.utils import wire

PyTree = Any

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.(?:msgpack\.z|dwc)$")
_META_RE = re.compile(r"^ckpt_(\d+)\.json$")

# Chunked-format framing: header magic, then streamed DWZ1 chunk frames,
# then the JSON manifest, then a fixed-size footer locating the manifest.
# Footer v1 (b"DWCK"): no integrity data.  Footer v2 (b"DWC2") adds a
# CRC32 of the manifest bytes; v2 manifests carry a CRC32 per chunk frame.
# The header magic stays DWCK0001 for both — readers dispatch on the TAIL.
_DWC_MAGIC = b"DWCK0001"
_DWC_FOOTER = struct.Struct("<QI4s")  # manifest_offset u64, manifest_len u32, b"DWCK"
_DWC2_FOOTER = struct.Struct("<QII4s")  # + manifest_crc32 u32, b"DWC2"
CHUNK_BYTES = 4 << 20  # bound on raw bytes per compression/IO unit
_BLOB_SUFFIXES = (".dwc", ".msgpack.z")

# Exception shapes a corrupt/truncated blob can surface as anywhere in the
# read path (footer parse, manifest decode, chunk inflate, flax restore).
# OSErrors are deliberately excluded: an unreadable DISK is an environment
# problem the fallback must not paper over with an older checkpoint.
CorruptionError = (
    ValueError,  # includes json.JSONDecodeError and flax mismatches
    KeyError,
    IndexError,
    TypeError,
    struct.error,
    zlib.error,
    EOFError,
    OverflowError,
)


# ---------------------------------------------------------------------------
# state-dict flattening


def _flatten_state_dict(sd: Any, prefix: Tuple[str, ...] = ()) -> Iterator[
    Tuple[Tuple[str, ...], Any]
]:
    if isinstance(sd, dict):
        if not sd:
            # An empty dict IS a leaf: optax's EmptyState (and any empty
            # flax collection) serializes to {} — dropping it would
            # desync flax's list-length check on restore
            # (opt_state = (ScaleByAdamState, EmptyState)).
            yield prefix, {}
        for k in sorted(sd):
            yield from _flatten_state_dict(sd[k], prefix + (str(k),))
    else:
        yield prefix, sd


def _dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax's extension dtypes (bfloat16, fp8, ...)

        return np.dtype(getattr(ml_dtypes, name))


def snapshot_state(state: PyTree) -> dict:
    """TrainState → flat host snapshot ``{('a','b'): np.ndarray | scalar}``.

    This is the ONLY step that must run on the training thread for an
    async save: every array leaf is copied to host memory (``copy=True``
    — on the CPU backend ``np.asarray`` may alias the device buffer, and a
    donated buffer reused by the next step would corrupt an in-flight
    write).  Everything downstream works off this immutable snapshot.
    Large-leaf copies run threaded (numpy releases the GIL on contiguous
    copies; measured 2.2× on 2 cores) — this IS the async save's entire
    training-thread stall, so its speed is the stall.
    """
    out = {}
    big = []  # (path, leaf) copies worth parallelizing
    for path, leaf in _flatten_state_dict(serialization.to_state_dict(state)):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_replicated:
            # Checkpoints store the CANONICAL gathered layout — a ZeRO-
            # sharded state reaching this point means the caller skipped
            # the gather (Trainer.save runs StateLayout.canonical, a
            # collective every process joins, before handing state to the
            # checkpointer).  Keying on replication alone catches BOTH
            # failure shapes: multi-host sharded leaves (np.array would
            # fail deep in jax with no hint at the contract) and
            # single-host sharded leaves, which np.array would happily
            # serialize — silently writing chunked moments that cannot
            # restore into a fresh or differently-sized run.  Replicated
            # leaves pass everywhere: np.array reads them from the local
            # shard, which IS the canonical layout this format stores.
            raise ValueError(
                f"checkpoint leaf {'/'.join(path)} is not replicated "
                f"(sharded run layout?) — gather to the canonical layout "
                f"first (parallel/shard_update.py:StateLayout.canonical; "
                f"docs/SHARDING.md)"
            )
        if isinstance(leaf, dict):  # empty-dict leaf (see _flatten_state_dict)
            out[path] = {}
        elif isinstance(leaf, np.generic):
            # np scalars first: np.int64 subclasses int on some numpy
            # builds and would otherwise leak into the (json) branch,
            # where json.dumps rejects it — keep dtype via a 0-d array.
            out[path] = np.array(leaf)
        elif leaf is None or isinstance(leaf, (bool, int, float, str)):
            out[path] = leaf
        elif getattr(leaf, "nbytes", 0) >= (1 << 20):
            big.append((path, leaf))
        else:
            out[path] = np.array(leaf, copy=True)
    if len(big) == 1:
        path, leaf = big[0]
        out[path] = np.array(leaf, copy=True)
    elif big:
        copies = wire._get_pool().map(
            lambda pl: np.array(pl[1], copy=True), big
        )
        for (path, _), copy in zip(big, copies):
            out[path] = copy
    return out


def _unflatten(flat: dict) -> dict:
    root: dict = {}
    for path, leaf in flat.items():
        node = root
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = leaf
    return root


# ---------------------------------------------------------------------------
# chunked writer / reader


def _leaf_chunks(arr: np.ndarray, chunk_bytes: int) -> List[memoryview]:
    """Zero-copy uint8 views over ``arr``'s raw bytes, ≤ chunk_bytes each."""
    flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
    mv = memoryview(flat)
    return [mv[i : i + chunk_bytes] for i in range(0, len(mv), chunk_bytes)] or [
        mv
    ]


def _write_chunked(
    f,
    snap: dict,
    chunk_bytes: int,
    compression: str,
    lineage: Optional[dict] = None,
) -> None:
    """Stream the snapshot through the wire codec into open file ``f``."""
    if compression not in ("adaptive", "always", "store"):
        raise ValueError(f"unknown checkpoint compression {compression!r}")
    level = {"adaptive": wire.LEVEL, "always": wire.LEVEL, "store": 0}[
        compression
    ]
    f.write(_DWC_MAGIC)
    offset = len(_DWC_MAGIC)
    leaves = []
    array_entries = []  # (manifest entry, chunk memoryviews)
    for path, leaf in snap.items():
        if isinstance(leaf, dict):
            leaves.append({"path": list(path), "kind": "empty_dict"})
            continue
        if leaf is None or isinstance(leaf, (bool, int, float, str)):
            leaves.append({"path": list(path), "kind": "json", "value": leaf})
            continue
        arr = np.asarray(leaf)
        if arr.dtype == object:
            raise TypeError(
                f"checkpoint leaf {'/'.join(path)} has object dtype — not "
                f"serializable as raw bytes"
            )
        entry = {
            "path": list(path),
            "kind": "array",
            "dtype": arr.dtype.name,
            "shape": list(arr.shape),
            "chunks": [],  # [offset, comp_len, raw_len, frame_crc32]
        }
        leaves.append(entry)
        array_entries.append((entry, _leaf_chunks(arr, chunk_bytes)))

    def all_chunks():
        for _, chunks in array_entries:
            yield from chunks

    sizes = [
        (entry, [len(c) for c in chunks]) for entry, chunks in array_entries
    ]
    frames = wire.compress_chunks(
        all_chunks(), level=level, adaptive=(compression == "adaptive")
    )
    for entry, raw_lens in sizes:
        for raw_len in raw_lens:
            frame = next(frames)
            f.write(frame)
            # CRC the frame AS STORED (post-compression): verification can
            # then run at read speed without inflating anything, and any
            # on-disk flip — payload, frame header, stored block — trips it.
            entry["chunks"].append(
                [offset, len(frame), raw_len, zlib.crc32(frame)]
            )
            offset += len(frame)
    # Manifest v3 = v2 + the lineage record (ISSUE 17): provenance travels
    # INSIDE the blob, surviving sidecar loss.  v1/v2 readers that ignore
    # unknown manifest keys restore v3 blobs unchanged.
    doc: dict = {"version": 3, "leaves": leaves}
    if lineage is not None:
        doc["lineage"] = lineage
    manifest = json.dumps(doc).encode()
    f.write(manifest)
    f.write(
        _DWC2_FOOTER.pack(offset, len(manifest), zlib.crc32(manifest), b"DWC2")
    )


def _parse_dwc(data: bytes, path: str) -> Tuple[dict, int]:
    """(manifest, manifest_offset) from a whole ``.dwc`` byte string.

    Dispatches on the footer tail: ``DWC2`` footers verify the manifest's
    CRC32 before a single manifest byte is trusted (a flipped shape digit
    must fail HERE, not as a petabyte ``np.empty``); legacy ``DWCK``
    footers parse structurally as before.
    """
    if len(data) < len(_DWC_MAGIC) + _DWC_FOOTER.size or not data.startswith(
        _DWC_MAGIC
    ):
        raise ValueError(f"{path}: not a DWCK chunked checkpoint")
    tail = data[-4:]
    if tail == b"DWC2":
        man_off, man_len, man_crc, _ = _DWC2_FOOTER.unpack_from(
            data, len(data) - _DWC2_FOOTER.size
        )
        footer_size = _DWC2_FOOTER.size
    elif tail == b"DWCK":
        man_off, man_len, man_crc = (
            *_DWC_FOOTER.unpack_from(data, len(data) - _DWC_FOOTER.size)[:2],
            None,
        )
        footer_size = _DWC_FOOTER.size
    else:
        raise ValueError(f"{path}: truncated or corrupt checkpoint footer")
    if man_off + man_len > len(data) - footer_size:
        raise ValueError(f"{path}: truncated or corrupt checkpoint footer")
    man_bytes = data[man_off : man_off + man_len]
    if man_crc is not None and zlib.crc32(man_bytes) != man_crc:
        raise ValueError(
            f"{path}: corrupt checkpoint manifest (CRC mismatch)"
        )
    return json.loads(man_bytes), man_off


def _entry_chunks(entry: dict) -> Iterator[Tuple[int, int, int, Optional[int]]]:
    """(offset, comp_len, raw_len, crc_or_None) per chunk — v1 manifests
    carry 3-element chunk rows (no CRC), v2 carry 4."""
    for row in entry["chunks"]:
        off, comp_len, raw_len = row[:3]
        yield off, comp_len, raw_len, (row[3] if len(row) > 3 else None)


def _read_chunked(path: str, target: PyTree) -> PyTree:
    with open(path, "rb") as f:
        data = f.read()
    manifest, man_off = _parse_dwc(data, path)
    flat = {}
    for entry in manifest["leaves"]:
        path_t = tuple(entry["path"])
        if entry["kind"] == "empty_dict":
            flat[path_t] = {}
            continue
        if entry["kind"] == "json":
            flat[path_t] = entry["value"]
            continue
        dtype = _dtype(entry["dtype"])
        shape = tuple(entry["shape"])
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        # Cross-check the manifest against itself before trusting it with
        # an allocation: on v1 blobs (no manifest CRC) a corrupt shape or
        # raw_len must fail as a ValueError, not an absurd np.empty.
        raw_total = sum(raw for _, _, raw, _ in _entry_chunks(entry))
        if raw_total != nbytes:
            raise ValueError(
                f"{path}: leaf {'/'.join(entry['path'])} manifest is "
                f"inconsistent ({raw_total} chunk bytes vs {nbytes} from "
                f"shape) — corrupt manifest"
            )
        buf = np.empty(nbytes, np.uint8)
        mv = memoryview(buf)
        pos = 0
        for off, comp_len, raw_len, crc in _entry_chunks(entry):
            if off + comp_len > man_off:
                raise ValueError(f"{path}: chunk overruns manifest")
            frame = data[off : off + comp_len]
            if crc is not None and zlib.crc32(frame) != crc:
                raise ValueError(
                    f"{path}: corrupt chunk at offset {off} (CRC mismatch) "
                    f"in leaf {'/'.join(entry['path'])}"
                )
            n = wire.decompress_into(frame, mv[pos : pos + raw_len])
            if n != raw_len:
                raise ValueError(
                    f"{path}: chunk inflated to {n} bytes, manifest says "
                    f"{raw_len}"
                )
            pos += raw_len
        if pos != nbytes:
            raise ValueError(
                f"{path}: leaf {'/'.join(entry['path'])} assembled {pos} of "
                f"{nbytes} bytes"
            )
        flat[path_t] = buf.view(dtype).reshape(shape)
    return serialization.from_state_dict(target, _unflatten(flat))


def verify_checkpoint(path: str) -> dict:
    """Integrity-check a checkpoint blob WITHOUT restoring it.

    For v2 chunked blobs this verifies the footer, the manifest CRC, and
    every chunk frame's CRC — one sequential read, no decompression, no
    target pytree needed.  v1 chunked blobs get the structural checks only
    (``verified_chunks`` reports 0 — there is nothing recorded to verify
    against); monolithic blobs are verified by inflating them (the DWZ1
    frame is its own integrity check: truncation or corruption fails the
    inflate).  Raises :data:`CorruptionError` members on corruption;
    returns a summary dict on success.
    """
    if path.endswith(".dwc"):
        with open(path, "rb") as f:
            data = f.read()
        manifest, man_off = _parse_dwc(data, path)
        checked = 0
        chunks = 0
        for entry in manifest["leaves"]:
            if entry["kind"] != "array":
                continue
            # Same manifest self-consistency check as the reader: chunk
            # raw bytes must add up to the declared shape.
            nbytes = int(
                np.prod(tuple(entry["shape"]), dtype=np.int64)
            ) * _dtype(entry["dtype"]).itemsize
            raw_total = sum(raw for _, _, raw, _ in _entry_chunks(entry))
            if raw_total != nbytes:
                raise ValueError(
                    f"{path}: leaf {'/'.join(entry['path'])} manifest is "
                    f"inconsistent ({raw_total} chunk bytes vs {nbytes} "
                    f"from shape) — corrupt manifest"
                )
            for off, comp_len, raw_len, crc in _entry_chunks(entry):
                if off + comp_len > man_off:
                    raise ValueError(f"{path}: chunk overruns manifest")
                chunks += 1
                if crc is None:
                    continue
                if zlib.crc32(data[off : off + comp_len]) != crc:
                    raise ValueError(
                        f"{path}: corrupt chunk at offset {off} "
                        f"(CRC mismatch) in leaf {'/'.join(entry['path'])}"
                    )
                checked += 1
        return {
            "format": "chunked",
            "manifest_version": int(manifest.get("version", 1)),
            "chunks": chunks,
            "verified_chunks": checked,
        }
    with open(path, "rb") as f:
        blob = wire.decompress(f.read())
    return {"format": "monolithic", "bytes": len(blob), "verified_chunks": 0}


def _footer_ok(path: str) -> bool:
    """Cheap liveness check for prune: footer + manifest (CRC'd on v2)
    parse.  Reads only the tail of the file — O(manifest), not O(blob)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            head = f.read(len(_DWC_MAGIC))
            if head != _DWC_MAGIC:
                return False
            f.seek(max(0, size - _DWC2_FOOTER.size))
            foot = f.read()
            if foot.endswith(b"DWC2"):
                man_off, man_len, man_crc, _ = _DWC2_FOOTER.unpack(
                    foot[-_DWC2_FOOTER.size :]
                )
            elif foot.endswith(b"DWCK"):
                man_off, man_len = _DWC_FOOTER.unpack(
                    foot[-_DWC_FOOTER.size :]
                )[:2]
                man_crc = None
            else:
                return False
            if man_off + man_len > size:
                return False
            f.seek(man_off)
            man_bytes = f.read(man_len)
        if man_crc is not None and zlib.crc32(man_bytes) != man_crc:
            return False
        json.loads(man_bytes)
        return True
    except (OSError, *CorruptionError):
        return False


def _step_files_verify(ckpt_dir: str, step: int) -> bool:
    """Do a step's on-disk files pass integrity verification?

    Gates quarantine: a restore error whose blob AND sidecar verify clean
    is a *caller* problem (most commonly restoring into a different model
    config — flax raises the same ValueError shape as corruption), and
    quarantining would walk every healthy checkpoint into ``*.bad``.
    Note v1 chunked blobs carry no CRCs, so their payload corruption is
    unverifiable — they re-raise instead of quarantining, which errs on
    the side of keeping files.
    """
    try:
        path, _ = checkpoint_path(ckpt_dir, step)
        verify_checkpoint(path)
        meta_path = os.path.join(ckpt_dir, f"ckpt_{step}.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                json.load(f)
        return True
    except (OSError, *CorruptionError):
        return False


def quarantine_checkpoint(ckpt_dir: str, step: int) -> List[str]:
    """Rename a corrupt step's blob (and metadata sidecar) to ``*.bad``.

    Quarantined files no longer match the checkpoint patterns: they are
    invisible to :func:`latest_step`, never count toward ``keep``, and are
    never re-tried by restore — but the bytes stay on disk as evidence.
    Returns the renamed paths.
    """
    renamed = []
    for suffix in (*_BLOB_SUFFIXES, ".json"):
        path = os.path.join(ckpt_dir, f"ckpt_{step}{suffix}")
        if os.path.exists(path):
            os.replace(path, path + ".bad")
            renamed.append(path + ".bad")
    return renamed


# ---------------------------------------------------------------------------
# save / restore API


def save_checkpoint(
    ckpt_dir: str,
    state: PyTree,
    step: int,
    metadata: Optional[dict] = None,
    keep: int = 3,
    format: str = "chunked",
    chunk_bytes: int = CHUNK_BYTES,
    compression: str = "adaptive",
) -> Optional[str]:
    """Write ``state`` as checkpoint ``step``; returns the path (None on
    non-zero processes, which skip the write — state is replicated)."""
    if jax.process_index() != 0:
        return None
    return save_snapshot(
        ckpt_dir,
        snapshot_state(state),
        step,
        metadata=metadata,
        keep=keep,
        format=format,
        chunk_bytes=chunk_bytes,
        compression=compression,
    )


def save_snapshot(
    ckpt_dir: str,
    snap: dict,
    step: int,
    metadata: Optional[dict] = None,
    keep: int = 3,
    format: str = "chunked",
    chunk_bytes: int = CHUNK_BYTES,
    compression: str = "adaptive",
) -> str:
    """Write an already-host-resident snapshot (from :func:`snapshot_state`).

    This is the body the AsyncCheckpointer's writer thread runs; the
    caller is responsible for the process-0 gate.  Atomicity: metadata
    json renamed first, then blob tmp + fsync + rename, then directory
    fsync, then prune — a crash at ANY point leaves every previously
    completed checkpoint restorable and never a partial blob under a
    final name (tests/test_checkpoint_format.py kills each stage).
    """
    if format not in ("chunked", "monolithic"):
        raise ValueError(f"unknown checkpoint format {format!r}")
    os.makedirs(ckpt_dir, exist_ok=True)
    name = f"ckpt_{step}.dwc" if format == "chunked" else f"ckpt_{step}.msgpack.z"
    # Lineage (ISSUE 17): every save carries a provenance record.  The
    # trainer supplies one (run id + config hash); bare callers get a
    # synthesized record so downstream NEVER sees an absent lineage on a
    # fresh save.  saved_at is (re)stamped HERE — the durable-write
    # moment is what the freshness/deploy-latency gauges anchor on.
    lin = (metadata or {}).get("lineage")
    if not isinstance(lin, dict):
        lin = _lineage.make_lineage(step)
    lin = dict(lin, step=int(step), saved_at=time.time())
    meta = dict(metadata or {}, step=step, lineage=lin)
    meta_tmp = os.path.join(ckpt_dir, f".meta_{step}.tmp")
    try:
        with open(meta_tmp, "w") as f:
            json.dump(meta, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(meta_tmp, os.path.join(ckpt_dir, f"ckpt_{step}.json"))
    except BaseException:
        if os.path.exists(meta_tmp):
            os.unlink(meta_tmp)
        raise
    _chaos = _chaos_active()
    if _chaos is not None:
        # Fault injection (resilience/chaos.py, inert without DDLPC_CHAOS):
        # a scheduled disk-full raises HERE, inside the write path proper,
        # so it surfaces exactly where a real ENOSPC would — through the
        # AsyncCheckpointer's re-raise-on-training-thread contract.
        _chaos.on_checkpoint_save()
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            if format == "chunked":
                _write_chunked(f, snap, chunk_bytes, compression, lineage=lin)
            else:
                f.write(
                    wire.compress(serialization.msgpack_serialize(_unflatten(snap)))
                )
            f.flush()
            # fsync before rename: os.replace alone is atomic against
            # process crashes but not power loss — an un-synced blob could
            # survive the rename empty while _prune already deleted the
            # older checkpoints.
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(ckpt_dir, name))
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    # Persist both renames before pruning the fallback checkpoints.
    dir_fd = os.open(ckpt_dir, os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)
    _prune(ckpt_dir, keep)
    final = os.path.join(ckpt_dir, name)
    if _chaos is not None:
        # Post-rename bit-flip: corrupts the DURABLE blob, which is the
        # case the CRC manifest + restore fallback must survive.
        _chaos.on_checkpoint_written(final)
    return final


def _steps(ckpt_dir: str) -> list[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    out = set()
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            out.add(int(m.group(1)))
    return sorted(out)


def _newest_verifiable_step(ckpt_dir: str, live: List[int]) -> Optional[int]:
    """Newest step whose blob passes the cheap footer check — the step a
    restore would actually land on if the newer ones are corrupt."""
    for step in reversed(live):
        try:
            path, fmt = checkpoint_path(ckpt_dir, step)
        except FileNotFoundError:
            continue
        # Monolithic blobs have no cheap check (inflating the whole blob
        # per prune is not): treat as verifiable, matching pre-CRC behavior.
        if fmt != "chunked" or _footer_ok(path):
            return step
    return None


def _prune(ckpt_dir: str, keep: int) -> None:
    live = _steps(ckpt_dir)
    doomed = live[:-keep] if keep > 0 else []
    if doomed:
        # Never delete the newest VERIFIABLE checkpoint: if every blob in
        # the keep window is corrupt (e.g. a bad disk flipped bits in the
        # newest writes), the step restore would fall back to must survive
        # the prune — otherwise ``keep`` compounds corruption into total
        # loss.  Quarantined ``*.bad`` files never match _CKPT_RE, so they
        # neither count toward ``keep`` nor shadow a live step here.
        protect = _newest_verifiable_step(ckpt_dir, live)
        if protect is not None and protect in doomed:
            doomed = [s for s in doomed if s != protect]
    for step in doomed:
        for suffix in (*_BLOB_SUFFIXES, ".json"):
            path = os.path.join(ckpt_dir, f"ckpt_{step}{suffix}")
            if os.path.exists(path):
                os.unlink(path)
    # Sweep metadata orphaned by a crash between the json and blob renames
    # (save order writes json first) — a .json with no blob is never a
    # restorable step and would otherwise accumulate forever.
    alive = set(live) - set(doomed)
    for name in os.listdir(ckpt_dir):
        m = _META_RE.match(name)
        if m and int(m.group(1)) not in alive:
            os.unlink(os.path.join(ckpt_dir, name))
        elif name.endswith(".tmp"):
            # Debris from a hard kill mid-write (the exception cleanup
            # never ran).  Safe under the single-writer invariant: _prune
            # runs after this save's own renames, so any surviving .tmp
            # is a dead write.
            os.unlink(os.path.join(ckpt_dir, name))


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = _steps(ckpt_dir)
    return steps[-1] if steps else None


def checkpoint_path(ckpt_dir: str, step: int) -> Tuple[str, str]:
    """(path, format) of step's blob; chunked preferred when both exist."""
    for suffix, fmt in ((".dwc", "chunked"), (".msgpack.z", "monolithic")):
        path = os.path.join(ckpt_dir, f"ckpt_{step}{suffix}")
        if os.path.exists(path):
            return path, fmt
    raise FileNotFoundError(f"no blob for step {step} in {ckpt_dir}")


def peek_metadata(ckpt_dir: str, step: Optional[int] = None) -> dict:
    """Read a checkpoint's JSON sidecar without touching the blob — for
    callers that need metadata (e.g. input_channels) BEFORE they can build
    the restore target."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    meta_path = os.path.join(ckpt_dir, f"ckpt_{step}.json")
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


def read_manifest_lineage(path: str) -> Optional[dict]:
    """The lineage record embedded in a ``.dwc`` blob's manifest, or None
    (pre-v3 blob, no lineage key, or any read/parse failure — lineage
    recovery must never turn a restorable blob into an error).  Reads only
    the file tail, like :func:`_footer_ok`."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            head = f.read(len(_DWC_MAGIC))
            if head != _DWC_MAGIC:
                return None
            f.seek(max(0, size - _DWC2_FOOTER.size))
            foot = f.read()
            if foot.endswith(b"DWC2"):
                man_off, man_len, man_crc, _ = _DWC2_FOOTER.unpack(
                    foot[-_DWC2_FOOTER.size :]
                )
            elif foot.endswith(b"DWCK"):
                man_off, man_len = _DWC_FOOTER.unpack(
                    foot[-_DWC_FOOTER.size :]
                )[:2]
                man_crc = None
            else:
                return None
            if man_off + man_len > size:
                return None
            f.seek(man_off)
            man_bytes = f.read(man_len)
        if man_crc is not None and zlib.crc32(man_bytes) != man_crc:
            return None
        lin = json.loads(man_bytes).get("lineage")
        return lin if isinstance(lin, dict) else None
    except (OSError, *CorruptionError):
        return None


def _restore_step(ckpt_dir: str, target: PyTree, step: int) -> Tuple[PyTree, dict]:
    path, fmt = checkpoint_path(ckpt_dir, step)
    if fmt == "chunked":
        state = _read_chunked(path, target)
    else:
        with open(path, "rb") as f:
            state = serialization.from_bytes(target, wire.decompress(f.read()))
    meta_path = os.path.join(ckpt_dir, f"ckpt_{step}.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    # Lineage degradation contract (ISSUE 17): EVERY restore's metadata
    # carries a lineage dict.  Sidecar first (both formats write it),
    # then the v3 manifest (survives sidecar loss), then the explicit
    # unknown marker — pre-lineage checkpoints restore and serve, with
    # downstream gauges degrading instead of crashing.
    if not isinstance(meta.get("lineage"), dict):
        lin = read_manifest_lineage(path) if fmt == "chunked" else None
        meta = dict(meta, lineage=lin or _lineage.unknown_lineage(step))
    return state, meta


def restore_checkpoint(
    ckpt_dir: str,
    target: PyTree,
    step: Optional[int] = None,
    fallback: bool = True,
) -> Tuple[PyTree, dict]:
    """Restore (state, metadata).  ``target`` supplies the pytree structure
    (a freshly-initialized TrainState); ``step=None`` takes the newest.
    One reader for both formats: the serving engine's hot reload and the
    predict CLI restore pre-chunked runs through this same dispatch.

    **Integrity fallback** (``fallback=True``, the default): a corrupt or
    truncated blob is quarantined (renamed ``*.bad`` — never retried,
    never counted toward ``keep``) with a warning, and the restore moves
    to the next-newest checkpoint.  Every entry point — trainer resume,
    serve ``/reload``, the predict CLI — inherits this, so a flipped bit
    in the newest checkpoint can cost at most one checkpoint interval,
    never the run.  Only when NOTHING restorable remains does the original
    corruption error surface.  An explicit ``step=`` restores that step or
    fails (quarantining it if corrupt) — asking for a specific blob and
    silently receiving a different one would be worse than the error.
    """
    explicit = step is not None
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    quarantined: List[int] = []
    while True:
        try:
            state, meta = _restore_step(ckpt_dir, target, step)
        except CorruptionError as e:
            if _step_files_verify(ckpt_dir, step):
                # The files are intact: this error is the CALLER's
                # (structure mismatch, wrong target) — falling back would
                # fail identically on every older checkpoint while
                # quarantining the whole directory.  Surface it.
                raise
            bad = quarantine_checkpoint(ckpt_dir, step)
            warnings.warn(
                f"checkpoint step {step} in {ckpt_dir} is corrupt "
                f"({type(e).__name__}: {e}); quarantined "
                f"{[os.path.basename(b) for b in bad]}"
                + ("" if explicit else " — falling back to the next-newest"),
                RuntimeWarning,
                stacklevel=2,
            )
            quarantined.append(step)
            nxt = None if explicit or not fallback else latest_step(ckpt_dir)
            if nxt is None:
                raise ValueError(
                    f"checkpoint step {step} is corrupt and no fallback "
                    f"remains in {ckpt_dir} "
                    f"(quarantined steps: {quarantined}): {e}"
                ) from e
            step = nxt
            continue
        if quarantined:
            meta = dict(meta, quarantined_steps=quarantined)
        return state, meta
