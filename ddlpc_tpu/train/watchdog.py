"""Stall watchdog — bounded failure detection for training runs.

The reference has no failure handling at all (SURVEY §5): a dead peer leaves
the server blocked in ``recv`` forever (кластер.py:215-220) and an EOF turns
into a crash two frames later (кластер.py:99-100).  The SPMD equivalent of
that pathology is a hung collective: one lost host and every other process
in the mesh waits in the runtime, silently, indefinitely.

A watchdog cannot *recover* a lost SPMD peer (the mesh is static by
construction — that is what makes the collectives fast), but it can turn an
unbounded silent hang into a bounded, diagnosable failure:

- the training loop ``beat()``s on every data fetch and step dispatch;
- a daemon thread checks the heartbeat's age; past ``timeout_s`` it writes a
  diagnosis (last beat tag + age + the Python stacks of every thread, via
  ``faulthandler``) to stderr and ``<workdir>/stall.log``;
- ``action='abort'`` then exits the process with a distinctive status so a
  supervisor (the cluster scheduler that launched the job) can restart it —
  which resumes from the latest checkpoint (train/checkpoint.py): detect →
  die → restart → resume is the recovery story, matching how static-mesh
  TPU training recovers in practice.

The default ``action='dump'`` only diagnoses (repeating at most once per
timeout window), which is the safe default for interactive runs.
"""

from __future__ import annotations

import contextlib
import faulthandler
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Iterator, Optional


class StallWatchdog:
    """Detects when a heartbeat goes quiet for longer than ``timeout_s``.

    Use as a context manager around the training loop; call :meth:`beat`
    from the loop.  ``timeout_s <= 0`` disables everything (no thread).
    """

    def __init__(
        self,
        timeout_s: float,
        action: str = "dump",  # dump | abort
        log_path: Optional[str] = None,
        on_stall: Optional[Callable[[float, str], None]] = None,
        exit_code: int = 42,
        _exit=os._exit,  # injectable for tests
    ):
        if action not in ("dump", "abort"):
            raise ValueError(f"unknown watchdog action {action!r}")
        self.timeout_s = float(timeout_s)
        self.action = action
        self.log_path = log_path
        self.on_stall = on_stall
        self.exit_code = exit_code
        self._exit = _exit
        self._last = time.monotonic()
        self._tag = "init"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pause_depth = 0
        self._pause_lock = threading.Lock()
        self.stall_count = 0
        # Recent health alerts (obs/health.py HealthMonitor feeds these):
        # a stall diagnosis shows what health was doing just before the
        # hang — e.g. step-time regressions leading into a wedged
        # collective.  Bounded; mutation under its own lock (alerts arrive
        # from arbitrary threads).
        self._alerts: deque = deque(maxlen=32)
        self._alerts_lock = threading.Lock()

    # -- heartbeat ---------------------------------------------------------

    def beat(self, tag: str = "") -> None:
        """Mark liveness.  ``tag`` names the phase for the diagnosis line."""
        self._last = time.monotonic()
        if tag:
            self._tag = tag

    def record_alert(self, record: dict) -> None:
        """Remember a structured health alert (flat JSONL record shape) for
        the next stall diagnosis.  Never raises — diagnostics must not break
        the loop being observed."""
        try:
            with self._alerts_lock:
                self._alerts.append(dict(record))
        except Exception:
            pass

    def recent_alerts(self) -> list:
        with self._alerts_lock:
            return list(self._alerts)

    @contextlib.contextmanager
    def paused(self, tag: str = "paused") -> Iterator[None]:
        """Suspend stall detection for a legitimately long, unbeaten phase
        (full-set evaluation, checkpoint serialization, image dumps) whose
        duration is unrelated to the per-step timeout.  Nests; re-arms with
        a fresh heartbeat on exit."""
        with self._pause_lock:
            self._pause_depth += 1
        self._tag = tag
        try:
            yield
        finally:
            # Beat before (and atomically with) the depth decrement: the
            # monitor reads _pause_depth under this lock, so it can never
            # observe depth==0 while _last is still stale by the whole
            # paused duration (which would fire a spurious stall right
            # after a long checkpoint/image dump).
            with self._pause_lock:
                self.beat(f"after_{tag}")
                self._pause_depth -= 1

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StallWatchdog":
        if self.timeout_s > 0 and self._thread is None:
            self.beat("start")
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="stall-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals ---------------------------------------------------------

    def _run(self) -> None:
        poll = max(self.timeout_s / 10.0, 0.05)
        while not self._stop.wait(poll):
            with self._pause_lock:
                if self._pause_depth > 0:
                    continue
            age = time.monotonic() - self._last
            if age < self.timeout_s:
                continue
            self.stall_count += 1
            self._diagnose(age)
            if self.on_stall is not None:
                self.on_stall(age, self._tag)
            if self.action == "abort":
                self._exit(self.exit_code)
            # dump mode: rearm so the next window diagnoses again rather
            # than spinning a report per poll tick.
            self.beat()

    def _diagnose(self, age: float) -> None:
        msg = (
            f"[watchdog] no heartbeat for {age:.1f}s "
            f"(timeout {self.timeout_s:.1f}s); last phase: {self._tag!r}. "
            f"Process {os.getpid()} thread stacks follow."
        )
        alerts = self.recent_alerts()
        streams = [sys.stderr]
        fh = None
        try:
            if self.log_path:
                fh = open(self.log_path, "a")
                streams.append(fh)
            for s in streams:
                print(msg, file=s, flush=True)
                if alerts:
                    print(
                        f"[watchdog] {len(alerts)} recent health alert(s) "
                        f"before the stall:",
                        file=s,
                        flush=True,
                    )
                    for rec in alerts:
                        try:
                            print("  " + json.dumps(rec), file=s, flush=True)
                        except Exception:
                            pass
                try:
                    # All-thread Python stacks: shows whether the loop is
                    # stuck in a device fetch, a collective, or host code.
                    faulthandler.dump_traceback(file=s)
                except Exception:
                    pass
        finally:
            if fh is not None:
                fh.close()
