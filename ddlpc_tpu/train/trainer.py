"""The training driver: the reference's server/worker script bodies
(кластер.py:690-790, 792-895) re-designed as one SPMD ``Trainer``.

Where the reference branches on hostname into a server loop and a worker
loop that differ only in which half of the socket protocol they call, here
every process runs the identical program over a shared device mesh; the
"protocol" is the compiled all-reduce inside the train step.  On top of the
reference's behavior (epoch loop, gradient-accumulated sync steps, per-epoch
loss/pixel-acc/timing logs, qualitative PNG dumps) this driver adds what the
reference lacks (SURVEY §5): held-out evaluation with mIoU (the north-star
metric), checkpoint/resume, and a config artifact per run.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import warnings
from typing import Dict, Optional

import jax
import numpy as np

from ddlpc_tpu.config import ExperimentConfig
from ddlpc_tpu.data import ShardedLoader, build_dataset
from ddlpc_tpu.data.loader import DeviceCachedLoader, eval_batches
from ddlpc_tpu.models import build_model_from_experiment
from ddlpc_tpu.ops.metrics import (
    accuracy_from_confusion,
    iou_per_class,
    mean_iou,
)
from ddlpc_tpu.parallel.mesh import initialize_distributed, make_mesh
from ddlpc_tpu.parallel.shard_update import (
    GSPMD_LAYOUT_FOR_LEVEL,
    StateLayout,
    resolve_shard_update,
)
from ddlpc_tpu.parallel.train_step import (
    create_train_state,
    make_eval_step,
    make_eval_step_gspmd,
    make_predict_fn,
    make_train_step,
    make_train_step_gspmd,
)
from ddlpc_tpu.obs import comm as obs_comm
from ddlpc_tpu.obs import flops as obs_flops
from ddlpc_tpu.obs import hbm as obs_hbm
from ddlpc_tpu.obs import lineage as obs_lineage
from ddlpc_tpu.obs.health import HealthMonitor
from ddlpc_tpu.obs.http import TelemetryServer
from ddlpc_tpu.obs.profiling import OnDemandProfiler
from ddlpc_tpu.obs.registry import MetricsRegistry
from ddlpc_tpu.obs.tracing import Tracer
from ddlpc_tpu.resilience import chaos as _chaos_mod
from ddlpc_tpu.resilience.protocol import EXIT_PREEMPTED, write_breadcrumb
from ddlpc_tpu.train import checkpoint as ckpt
from ddlpc_tpu.train.async_checkpoint import AsyncCheckpointer
from ddlpc_tpu.train.observability import (
    MetricsLogger,
    StageTimer,
    dump_prediction_triples,
    maybe_profile,
)
from ddlpc_tpu.train.optim import build_optimizer
from ddlpc_tpu.train.watchdog import StallWatchdog


class PreemptedRun(Exception):
    """Raised inside the epoch loop when a graceful preemption was
    requested (SIGTERM, :meth:`Trainer.request_preempt`, or a chaos
    ``preempt@N`` fault): carries where the run stopped so the emergency
    checkpoint can record the exact mid-epoch position."""

    def __init__(self, epoch: int, steps_done: int):
        super().__init__(f"preempted at epoch {epoch}, step {steps_done}")
        self.epoch = epoch
        self.steps_done = steps_done


class Trainer:
    """End-to-end training: data, mesh, compiled steps, logging, checkpoints.

    ``TrainConfig.micro_batch_size`` is per-replica (the reference's
    ``batch_size=1`` per node, кластер.py:686); the global micro-batch is
    that times the data-axis size, and one optimizer step consumes
    ``sync_period`` micro-batches (кластер.py:685).
    """

    def __init__(self, cfg: ExperimentConfig, resume: bool = True):
        initialize_distributed()
        self.cfg = cfg
        if cfg.model.num_classes != cfg.data.num_classes:
            raise ValueError(
                f"model.num_classes={cfg.model.num_classes} != "
                f"data.num_classes={cfg.data.num_classes}: the loss would "
                f"silently clip out-of-range labels and mIoU would drop them"
            )
        if cfg.data.device_cache and cfg.data.augment:
            raise ValueError(
                "data.device_cache and data.augment are mutually exclusive: "
                "augmentation runs in the host gather path that the device "
                "cache bypasses"
            )
        if cfg.data.compact_upload and cfg.data.num_classes > 127:
            raise ValueError(
                f"data.compact_upload ships int8 labels, which cannot hold "
                f"num_classes={cfg.data.num_classes} (max 127)"
            )
        if cfg.data.lazy_tiles and cfg.data.device_cache:
            raise ValueError(
                "data.lazy_tiles and data.device_cache are mutually "
                "exclusive: the device cache uploads whole resident arrays, "
                "exactly what lazy_tiles exists to avoid"
            )
        if cfg.data.loader_workers > 1 and cfg.data.device_cache:
            raise ValueError(
                "data.loader_workers only affects the ShardedLoader host "
                "path; device_cache gathers batches on device, so worker "
                "threads have nothing to do — unset one of them"
            )
        self.mesh = make_mesh(cfg.parallel)
        data_size = self.mesh.shape[cfg.parallel.data_axis_name]
        self.global_micro_batch = cfg.train.micro_batch_size * data_size
        # Stochastic rounding's benefit is regime-dependent (measured, not
        # assumed — docs/QUANTIZATION.md round-3 table): at global super-batch
        # 32 it closes the int8 codec's entire convergence lag, but at the
        # flagship's 512 it COSTS −0.045 val mIoU vs nearest rounding (the
        # big batch already averages the rounding error away, so the injected
        # variance is pure noise).  Warn anyone combining it with a
        # large-batch operating point.
        global_super_batch = self.global_micro_batch * cfg.train.sync_period
        if (
            cfg.compression.mode != "none"
            and cfg.compression.rounding == "stochastic"
            and global_super_batch >= 256
        ):
            warnings.warn(
                f"rounding='stochastic' at global super-batch "
                f"{global_super_batch} (micro {cfg.train.micro_batch_size} x "
                f"sync {cfg.train.sync_period} x {data_size} replicas): the "
                f"committed A/B measured stochastic rounding HELPING at small "
                f"batch (closes int8's lag at super-batch 32) but COSTING "
                f"-0.045 val mIoU at super-batch 512 "
                f"(docs/QUANTIZATION.md round-3 table) — large batches "
                f"average quantization error away on their own; prefer "
                f"rounding='nearest' here",
                stacklevel=2,
            )

        self.train_ds, self.test_ds = build_dataset(cfg.data)
        self.model = build_model_from_experiment(cfg)
        self.spatial = cfg.parallel.space_axis_size > 1
        space = cfg.parallel.space_axis_name if self.spatial else None
        # ZeRO sharded-update level (parallel/shard_update.py,
        # docs/SHARDING.md): resolves to 'off'|'zero1'|'zero2'|'zero3'.
        # 'auto' picks zero2 for data meshes > 1 unless a codec
        # combination cannot compose (those fall back to 'off' — explicit
        # levels raise there instead).
        self.shard_update = resolve_shard_update(
            cfg.parallel.shard_update,
            cfg.compression,
            data_size,
            self.spatial,
            grad_clip_norm=cfg.train.grad_clip_norm,
        )

        # Unified telemetry (ddlpc_tpu/obs, docs/OBSERVABILITY.md): one
        # span tracer + one Prometheus-style registry per training process.
        # The tracer is constructed unconditionally — disabled it is a
        # near-free no-op — so every instrumentation site below stays
        # unconditional too.
        self.registry = MetricsRegistry()
        # Model lineage (ISSUE 17): one run id per Trainer construction +
        # the config hash every checkpoint this run saves will carry — the
        # identity the serving fleet resolves responses back to.
        self.run_id = obs_lineage.new_id()
        self.config_hash = obs_lineage.config_hash(
            json.dumps(cfg.to_dict(), sort_keys=True)
        )
        self.tracer = Tracer(
            enabled=cfg.train.trace and jax.process_index() == 0,
            service="train",
            jsonl_path=os.path.join(cfg.workdir, "spans.jsonl"),
            chrome_path=os.path.join(cfg.workdir, "trace.json"),
        )
        # Created before the loader so the ShardedLoader can thread its
        # per-stage host timings (loader_gather/cast/upload) into the same
        # epoch records as t_data/t_step (StageTimer is thread-safe; the
        # stages run on producer threads).  The tracer hook additionally
        # records every stage — including the loop's data/step stages and
        # the loader's per-stage hooks — as spans.
        self.timer = StageTimer(tracer=self.tracer)
        loader_cls = (
            DeviceCachedLoader if cfg.data.device_cache else ShardedLoader
        )
        # compact composes with BOTH transports: on the ShardedLoader it
        # shrinks the per-batch wire, on the DeviceCachedLoader it shrinks
        # the resident cache itself (44% of the fp32 HBM).
        loader_kw = (
            {"compact": cfg.data.compact_upload} if cfg.data.device_cache
            else {"compact": cfg.data.compact_upload,
                  "workers": cfg.data.loader_workers,
                  "native_gather": cfg.data.native_gather,
                  "timer": self.timer}
        )
        self.loader = loader_cls(
            self.train_ds,
            self.mesh,
            global_micro_batch=self.global_micro_batch,
            sync_period=cfg.train.sync_period,
            shuffle=cfg.data.shuffle,
            seed=cfg.data.seed,
            data_axis=cfg.parallel.data_axis_name,
            space_axis=space,
            **loader_kw,
        )
        # Step horizon for decaying LR schedules comes from the loader (one
        # source of truth for steps/epoch, including tail semantics).
        self.tx = build_optimizer(
            cfg.train, total_steps=cfg.train.epochs * len(self.loader)
        )

        h, w = cfg.data.image_size
        channels = self.train_ds.image_shape[-1]
        self.state = create_train_state(
            self.model,
            self.tx,
            jax.random.key(cfg.train.seed),
            (1, h, w, channels),
        )
        # Run layout: replicated, or — under the sharded update — the
        # level's persistent shards: Adam moments chunked 1/N (zero1/2/3),
        # plus the params themselves under zero3; the GSPMD path expresses
        # the same placements as NamedShardings (gspmd/gspmd_zero2/
        # gspmd_zero3).  ``layout`` converts both ways; checkpoints and
        # multi-host broadcasts always move the canonical (gathered)
        # layout, so on-disk state is layout-independent.
        layout_mode = (
            "replicated"
            if self.shard_update == "off"
            else (
                GSPMD_LAYOUT_FOR_LEVEL[self.shard_update]
                if self.spatial
                else self.shard_update
            )
        )
        self.layout = StateLayout(
            layout_mode,
            self.tx,
            self.state,
            self.mesh,
            cfg.parallel.data_axis_name,
        )
        self.state = self.layout.place(self.state)

        # Pure data mesh → hand-written shard_map collectives (reference-
        # parity codec semantics); data×space mesh → GSPMD, where XLA
        # partitions convs along H with automatic halo exchange.
        self.train_step = self._build_train_step()
        if self.spatial:
            self.eval_step = make_eval_step_gspmd(
                self.model,
                self.mesh,
                num_classes=cfg.model.num_classes,
                data_axis=cfg.parallel.data_axis_name,
                space_axis=space,
            )
        else:
            self.eval_step = make_eval_step(
                self.model,
                self.mesh,
                num_classes=cfg.model.num_classes,
                data_axis=cfg.parallel.data_axis_name,
            )
        self.predict = make_predict_fn(self.model)

        # Performance accounting (docs/PERF.md "Accounting"): a per-step
        # conv FLOP model traced once (no compute), live MFU/goodput and
        # per-device HBM gauges, and exact per-collective comm byte
        # counters for the configured codec/transport.  The comm-time
        # probe (a compiled sync-only program) is built lazily and sampled
        # at most once per epoch on the trace_sync cadence.
        self.perf: Optional[obs_flops.PerfAccountant] = None
        self.comm: Optional[obs_comm.CommAccountant] = None
        self._comm_probe = None
        self._comm_probed_epoch = False
        if cfg.train.perf_accounting:
            try:
                flops_per_step = obs_flops.conv_step_flops(
                    cfg, cfg.train.micro_batch_size, cfg.train.sync_period,
                    channels=channels,
                )
                if self.spatial:
                    # The trace is the UNPARTITIONED per-micro-batch
                    # program; under H-sharding each device executes
                    # ~1/space of those convs (halo recompute ignored —
                    # a few rows per conv).  Without this, spatial MFU
                    # overstates by space_axis_size.
                    flops_per_step //= cfg.parallel.space_axis_size
            except Exception as e:  # accounting must never kill the run
                warnings.warn(
                    f"per-step FLOP model unavailable ({type(e).__name__}: "
                    f"{e}); ddlpc_mfu will read 0",
                    stacklevel=2,
                )
                flops_per_step = 0
            peak, assumed = obs_flops.resolve_peak_flops(
                cfg.train.peak_flops_per_device
            )
            self.perf = obs_flops.PerfAccountant(
                self.registry,
                flops_per_step=flops_per_step,
                peak_flops=peak,
                peak_assumed=assumed,
                # Downtime inherited from a previous supervised attempt
                # (breadcrumb / resilience.jsonl) — read BEFORE this run's
                # first breadcrumb write, debited as category 'restart'.
                restart_gap_s=obs_flops.restart_gap_seconds(cfg.workdir),
            )
            obs_hbm.publish_hbm_gauges(
                self.registry,
                self.state,
                level=self.shard_update,
                n_shards=data_size,
                replicated_by_rule=self.layout.replicated_by_rule_bytes(),
            )
            if cfg.compression.transport == "ring" and cfg.compression.mode != "none":
                variant = "ring"
            elif self.spatial:
                variant = "gspmd"
            elif self.shard_update == "zero2":
                variant = "scatter"
            elif self.shard_update in ("zero1", "zero3"):
                variant = self.shard_update
            else:
                variant = "allreduce"
            # Canonical (unchunked) parameter shapes: under zero3 the
            # placed params are [N, K] chunks, but the wire accounting
            # and the probe model the sync over the logical grads.
            canonical_params = self.layout.param_avals
            n_params = obs_comm.tree_elements(canonical_params)
            from ddlpc_tpu.parallel.grad_sync import grad_bucket_groups

            n_buckets = len(
                grad_bucket_groups(
                    canonical_params, cfg.compression.bucket_mb
                )
            )
            self.comm = obs_comm.CommAccountant(
                self.registry,
                obs_comm.comm_plan(
                    n_params,
                    n_params,
                    cfg.compression,
                    data_size,
                    variant,
                    n_buckets=n_buckets,
                ),
                variant,
            )
            if not self.spatial and data_size > 1:
                # Shape-only closure: the probe must not pin the initial
                # (donated) param buffers alive.
                param_shapes = jax.tree.map(
                    lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype),
                    canonical_params,
                )
                self._comm_probe = obs_comm.make_comm_probe(
                    self.mesh,
                    cfg.compression,
                    param_shapes,
                    data_axis=cfg.parallel.data_axis_name,
                    scatter=self.shard_update in ("zero2", "zero3"),
                    seed=cfg.train.seed,
                )

        self.workdir = cfg.workdir
        self.ckpt_dir = os.path.join(self.workdir, "checkpoints")
        self.start_epoch = 0
        # Preemption-graceful shutdown state (docs/RESILIENCE.md): SIGTERM
        # (or request_preempt(), or a chaos preempt fault) sets the event;
        # the step loop finishes the in-flight step, then fit() writes an
        # emergency checkpoint recording the mid-epoch position and the
        # process exits with EXIT_PREEMPTED.  ``preempted`` is the flag
        # __main__ maps to that exit status.
        self._preempt = threading.Event()
        self._preempt_done = threading.Event()
        self._grace_timer: Optional[threading.Timer] = None
        self.preempted = False
        # Mid-epoch resume: the restore below may find an emergency
        # checkpoint taken ``mid_epoch_steps_done`` steps into an epoch —
        # train_epoch then draws-and-discards exactly that many batches
        # (the loader is epoch-seeded and deterministic), so the resumed
        # trajectory is bit-identical to an uninterrupted run's.
        self._skip_steps = 0
        self._skip_epoch = -1
        # Chaos fault injection (resilience/chaos.py): None unless the
        # DDLPC_CHAOS env var schedules faults; the step counter is
        # process-lifetime, matching the schedule's step semantics.
        self._chaos = _chaos_mod.active()
        self._chaos_step = 0
        # Lineage of the checkpoint this run resumed from (None on a cold
        # start; the explicit unknown marker on pre-lineage checkpoints).
        self.restored_lineage: Optional[dict] = None
        if resume:
            self._restore_synchronized()
        self.logger = MetricsLogger(
            self.workdir,
            run_config_json=cfg.to_json(),
            registry=self.registry,
        )
        # Failure detection (SURVEY §5: the reference has none and hangs
        # forever on a dead peer).  Armed by fit(); beats come from the
        # epoch loop's data/step stages.
        self.watchdog = StallWatchdog(
            timeout_s=cfg.train.stall_timeout_s,
            action=cfg.train.stall_action,
            log_path=os.path.join(self.workdir, "stall.log"),
            # Last breadcrumb before an abort(42): the supervisor reads it
            # to classify the exit even if stderr was lost.
            on_stall=lambda age, tag: (
                write_breadcrumb(
                    self.workdir, "stalled", stall_age_s=age, stall_tag=tag
                )
                if jax.process_index() == 0
                else None
            ),
        )
        # Health detectors (obs/health.py): EWMA step-time regression and
        # loss NaN/spike alerts, fed per epoch record, fanning out to the
        # JSONL stream, the registry, and the watchdog's diagnosis ring.
        self.health = HealthMonitor(
            logger=self.logger,
            registry=self.registry,
            watchdog=self.watchdog,
            service="train",
        )
        # On-demand profiling (obs/profiling.py): armed by SIGUSR2 (fit
        # installs the handler) or GET /debug/trace on the telemetry
        # endpoint; the step loop drives the capture over the next N steps
        # and the top-ops report lands in the workdir.
        self.profiler = OnDemandProfiler(
            out_dir=self.workdir,
            steps=cfg.train.profile_steps,
            logger=self.logger,
            enabled=jax.process_index() == 0,
        )
        self.telemetry: Optional[TelemetryServer] = None
        if cfg.train.telemetry_port >= 0 and jax.process_index() == 0:
            self.telemetry = TelemetryServer(
                self.registry,
                port=cfg.train.telemetry_port,
                health_fn=self._health_snapshot,
                arm_profile_fn=self._arm_profile,
            ).start()
        # Async by default: save() pays only the host snapshot; the chunk/
        # compress/fsync chain overlaps the next epoch's compute on a
        # writer thread, with a barrier (and error re-raise) on the next
        # save and at the end of fit() (train/async_checkpoint.py).
        self.checkpointer = AsyncCheckpointer(
            keep=cfg.train.keep_checkpoints,
            format=cfg.train.checkpoint_format,
            chunk_bytes=max(1, cfg.train.checkpoint_chunk_mb) << 20,
            compression=cfg.train.checkpoint_compression,
            background=cfg.train.checkpoint_async,
        )

    def _health_snapshot(self) -> dict:
        return {
            "status": "ok",
            "pid": os.getpid(),
            "alerts": list(self.health.alerts),
        }

    def _arm_profile(self, steps: int) -> dict:
        self.profiler.arm(steps if steps > 0 else None)
        return {
            "armed": True,
            "steps": self.profiler.steps,
            "note": (
                "capture spans the next N dispatched training steps; the "
                "top-ops report lands in the run workdir"
            ),
        }

    def close(self) -> None:
        """Release the telemetry endpoint and the tracer's file handles.

        fit() deliberately leaves both running — the endpoint stays
        scrapeable between/after fits and the tracer supports a
        subsequent fit — so a caller constructing multiple Trainers in
        one process (or binding a fixed telemetry_port twice) must close
        the old one.  Idempotent."""
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None
        self.tracer.close()

    def _build_train_step(self):
        cfg = self.cfg
        if getattr(cfg.parallel, "pipeline_stages", 1) > 1:
            raise ValueError(
                "pipeline_stages > 1 is not wired into the epoch Trainer: "
                "staged execution is host-scheduled (one program per stage, "
                "microbatch round-robin), which the Trainer's single-step "
                "loop cannot drive — build the step via "
                "parallel/pipeline.make_pipeline_train_step (bench.py "
                "--pipeline-ab shows the full driver loop); Trainer "
                "integration is a ROADMAP follow-on"
            )
        if self.spatial:
            return make_train_step_gspmd(
                self.model,
                self.tx,
                self.mesh,
                cfg.compression,
                data_axis=cfg.parallel.data_axis_name,
                space_axis=cfg.parallel.space_axis_name,
                remat=cfg.train.remat,
                seed=cfg.train.seed,
                shard_update=self.shard_update,
            )
        return make_train_step(
            self.model,
            self.tx,
            self.mesh,
            cfg.compression,
            data_axis=cfg.parallel.data_axis_name,
            remat=cfg.train.remat,
            seed=cfg.train.seed,
            shard_update=self.shard_update,
            # zero3's gather-on-demand restores chunks to these canonical
            # shapes; harmless (ignored) at every other level.
            param_avals=self.layout.param_avals,
        )

    def _restore_synchronized(self) -> None:
        """Resume with process 0 as the single source of truth.

        Only process 0 writes checkpoints (checkpoint.py), so on non-shared
        storage other hosts may see nothing — deciding locally would
        desynchronize the SPMD program (mismatched collective counts hang the
        pod).  Process 0 decides; both the resume epoch and the restored
        state are broadcast to every process.
        """
        if jax.process_count() == 1:
            if ckpt.latest_step(self.ckpt_dir) is not None:
                # The restore target only supplies pytree STRUCTURE (leaf
                # shapes come from the blob) — checkpoints store the
                # canonical gathered layout regardless of the run layout,
                # and place() re-chunks/re-shards for this run.  A corrupt
                # newest blob is quarantined and the restore falls back to
                # the next-newest inside restore_checkpoint itself.
                state, meta = ckpt.restore_checkpoint(self.ckpt_dir, self.state)
                self.state = self.layout.place(state)
                self.start_epoch = int(meta.get("epoch", -1)) + 1
                self.restored_lineage = meta.get("lineage")
                self._apply_mid_epoch(int(meta.get("mid_epoch_steps_done", 0)))
            return
        from jax.experimental import multihost_utils

        if jax.process_index() == 0 and ckpt.latest_step(self.ckpt_dir) is not None:
            state, meta = ckpt.restore_checkpoint(self.ckpt_dir, self.state)
            found, epoch_next = 1, int(meta.get("epoch", -1)) + 1
            skip = int(meta.get("mid_epoch_steps_done", 0))
            self.restored_lineage = meta.get("lineage")
        else:
            state, found, epoch_next, skip = None, 0, 0, 0
        # Separate found flag: a checkpoint with missing/epoch-less metadata
        # must still restore its weights (resuming at epoch 0), matching the
        # single-process branch.
        found, epoch_next, skip = (
            int(v)
            for v in multihost_utils.broadcast_one_to_all(
                np.array([found, epoch_next, skip], np.int32)
            )
        )
        if found:
            # The broadcast moves the CANONICAL layout (every process must
            # contribute a structurally identical pytree; under a sharded
            # run layout the local state's chunk shapes would not match the
            # full-layout restore).  canonical() is a compiled collective,
            # so EVERY process runs it — process 0 included, discarding the
            # result in favor of the restored state.
            template = self.layout.canonical(self.state)
            state = multihost_utils.broadcast_one_to_all(
                state if state is not None else template
            )
            self.state = self.layout.place(state)
            self.start_epoch = epoch_next
            self._apply_mid_epoch(skip)

    def _apply_mid_epoch(self, skip: int) -> None:
        """Arm the skip-replay for an emergency (mid-epoch) checkpoint.

        ``mid_epoch_steps_done`` in the metadata means the restored state
        already contains that many optimizer steps of epoch
        ``start_epoch`` — replaying them would double-apply updates, so
        train_epoch discards exactly that many loader batches first.  A
        recorded position at/past the epoch horizon (possible only if the
        dataset shrank between runs) counts as a completed epoch instead
        of resuming into an empty one.
        """
        if skip <= 0:
            return
        if skip >= len(self.loader):
            self.start_epoch += 1
            return
        self._skip_steps = skip
        self._skip_epoch = self.start_epoch

    # ------------------------------------------------------------------
    # preemption-graceful shutdown (docs/RESILIENCE.md)

    def request_preempt(self) -> None:
        """Begin a graceful preemption: the step loop finishes its
        in-flight step, writes an emergency checkpoint, drains telemetry,
        and ``fit`` returns with ``self.preempted`` set (the CLI maps it
        to exit status 43).  Also arms the grace-window watchdog: if the
        graceful path has not completed within
        ``TrainConfig.preempt_grace_s``, the process hard-exits — the
        last DURABLE checkpoint still resumes (writes are atomic), which
        beats being SIGKILLed mid-write by an impatient scheduler.
        Idempotent; safe from signal handlers and other threads."""
        if self._preempt.is_set():
            return
        self._preempt.set()
        if jax.process_index() == 0:
            write_breadcrumb(
                self.workdir,
                "preempt_requested",
                grace_s=self.cfg.train.preempt_grace_s,
            )
        t = threading.Timer(
            max(self.cfg.train.preempt_grace_s, 0.1), self._grace_expired
        )
        t.daemon = True
        t.start()
        self._grace_timer = t

    def _grace_expired(self) -> None:
        if self._preempt_done.is_set():
            return
        if jax.process_index() == 0:
            write_breadcrumb(self.workdir, "preempt_timeout")
        print(
            f"[preempt] grace window "
            f"({self.cfg.train.preempt_grace_s:.0f}s) expired before the "
            f"emergency checkpoint completed — hard exit; resuming from "
            f"the last durable checkpoint",
            flush=True,
        )
        os._exit(EXIT_PREEMPTED)

    def _graceful_preempt(self, epoch: int, steps_done: int) -> None:
        """The grace-window body: emergency checkpoint (with the exact
        mid-epoch position) + telemetry drain.  Runs between steps, so the
        state is at an optimizer-step boundary — the unit the skip-replay
        resume reasons in."""
        steps_per_epoch = len(self.loader)
        # State at an epoch boundary (steps_done 0 or a full epoch) needs
        # no mid-epoch bookkeeping; anything else records the position.
        completed = epoch if steps_done >= steps_per_epoch else epoch - 1
        meta = {
            "epoch": completed,
            "config": self.cfg.to_dict(),
            "input_channels": int(self.train_ds.image_shape[-1]),
            "preempted": True,
        }
        if 0 < steps_done < steps_per_epoch:
            meta["mid_epoch_steps_done"] = steps_done
        with self.watchdog.paused("preempt_checkpoint"):
            state = self.layout.canonical(self.state)
            step = int(jax.device_get(self.state.step))
            lin = obs_lineage.make_lineage(
                step, run_id=self.run_id, config_hash_hex=self.config_hash
            )
            meta["lineage"] = lin
            self.checkpointer.save(self.ckpt_dir, state, step=step, metadata=meta)
            # The emergency checkpoint must be DURABLE before the process
            # exits — this is the one save that cannot overlap anything.
            self.checkpointer.wait()
        self.logger.log(
            {
                "kind": "preempt",
                "epoch": epoch,
                "steps_done": steps_done,
                "ckpt_step": step,
            },
            echo=True,
        )
        self._log_lineage(
            "checkpoint_saved", lin, epoch=epoch, preempted=True
        )
        if jax.process_index() == 0:
            write_breadcrumb(
                self.workdir,
                "preempted",
                epoch=epoch,
                steps_done=steps_done,
                ckpt_step=step,
            )
        self.preempted = True
        self._preempt_done.set()
        if self._grace_timer is not None:
            self._grace_timer.cancel()
            self._grace_timer = None

    # ------------------------------------------------------------------

    def train_epoch(self, epoch: int) -> Dict[str, float]:
        self.loader.set_epoch(epoch)
        self._comm_probed_epoch = False
        losses, accs = [], []
        t_epoch = time.perf_counter()
        it = iter(self.loader)
        step_idx = 0
        skipped = 0
        if self._skip_steps and epoch == self._skip_epoch:
            # Skip-replay resume from an emergency (mid-epoch) checkpoint:
            # the restored state already contains these optimizer steps, so
            # draw-and-discard the same deterministic batches the
            # interrupted run consumed.  Costs host gather only — no
            # compute — and keeps the resumed trajectory bit-identical to
            # an uninterrupted run's (tests/test_preemption.py pins it).
            for _ in range(self._skip_steps):
                self.watchdog.beat("resume_skip")
                if next(it, None) is None:
                    break
                skipped += 1
            self._skip_steps = 0
        sync_every = self.cfg.train.trace_sync_every_steps
        while True:
            # Stage-resolved timing: the structured version of the
            # reference's per-stage time.time() prints (кластер.py:265-440).
            # "data" = host wait for the next uploaded super-batch (overlaps
            # compute via the loader's prefetch); "step" = compiled SPMD
            # step dispatch.  Both stages double as spans when tracing.
            if self._chaos is not None:
                self._chaos.on_data_fetch()
            self.watchdog.beat("data")
            with self.timer.stage("data"):
                batch = next(it, None)
            if batch is None:
                break
            self.watchdog.beat("step")
            with self.timer.stage("step"):
                self.state, metrics = self.train_step(self.state, *batch)
            losses.append(metrics["loss"])
            accs.append(metrics["pixel_acc"])
            step_idx += 1
            if self.comm is not None:
                # Exact logical collective bytes for this optimizer step
                # (obs/comm.py) — a handful of counter increments.
                self.comm.on_step()
            if self._chaos is not None:
                self._chaos_step += 1
                # kill/stall act inside on_step; preempt comes back as an
                # action so it runs the trainer's OWN graceful path.
                if "preempt" in self._chaos.on_step(self._chaos_step):
                    self.request_preempt()
            if self._preempt.is_set():
                # Step boundary reached with a preemption pending: stop
                # here — fit()'s handler writes the emergency checkpoint
                # recording this exact position.
                raise PreemptedRun(epoch, skipped + step_idx)
            # Sampled sync: every K steps a traced run blocks on the step
            # output so the trace carries REAL step latency at that cadence
            # — syncing every step would serialize the async dispatch
            # pipeline and measure a run that doesn't exist.
            if self.tracer.enabled and sync_every and step_idx % sync_every == 0:
                with self.tracer.span("step_sync", epoch=epoch, step=step_idx):
                    jax.block_until_ready(metrics["loss"])
                # Sampled fenced comm-time measurement, piggybacking on
                # the sync cadence (the pipeline is already drained here,
                # so the probe doesn't serialize dispatch): at most once
                # per epoch, feeding ddlpc_comm_fraction / the overlap-
                # headroom baseline (obs/comm.py).
                if self._comm_probe is not None and not self._comm_probed_epoch:
                    self._comm_probed_epoch = True
                    t_probe = time.perf_counter()
                    try:
                        with self.tracer.span("comm_probe", epoch=epoch):
                            self.comm.record_probe(self._comm_probe())
                    except Exception as e:  # accounting never kills the run
                        warnings.warn(
                            f"comm probe failed ({type(e).__name__}: {e}); "
                            f"disabling for this run",
                            stacklevel=2,
                        )
                        self._comm_probe = None
                    if self.perf is not None:
                        self.perf.debit(
                            "probe", time.perf_counter() - t_probe
                        )
            # Drive the on-demand profiler (no-op unless armed); the sync
            # closure drains this step's dispatch queue INTO the capture.
            self.profiler.step_done(
                sync=lambda m=metrics: jax.block_until_ready(m["loss"])
            )
        # One host sync per epoch (metrics stayed on device inside the loop).
        # Single batched device_get: per-element float() would cost one full
        # host round trip PER STEP on tunneled/remote devices (~115 ms each,
        # docs/PERF.md) — at flagship step times that is ~30% of the epoch.
        if not losses:
            # A zero-step epoch (empty dataset / loader) would otherwise
            # record NaN metrics and a meaningless step_time — fail loudly
            # with the cause instead (ADVICE r3).
            raise RuntimeError(
                f"epoch {epoch} produced 0 training steps: dataset has "
                f"{len(self.train_ds)} tiles against super-batch "
                f"{self.loader.super_batch} — the loader yielded no batches"
            )
        self.watchdog.beat("epoch_metrics_fetch")
        losses, accs = jax.device_get((losses, accs))
        losses = [float(l) for l in losses]
        accs = [float(a) for a in accs]
        epoch_time = time.perf_counter() - t_epoch
        steps = len(losses)
        record = {
            "epoch": epoch,
            "loss": float(np.mean(losses)),
            "pixel_acc": float(np.mean(accs)),
            "epoch_time_s": epoch_time,
            # Mean time per sync step — the reference's "среднее время на
            # батч" line (кластер.py:767-770).
            "step_time_s": epoch_time / steps,
            # Compute throughput: tile-instances processed (wrap-fill
            # duplicates included — they are real forward/backward work).
            # ``steps`` not len(loader): a skip-replay resume computes only
            # the remaining steps of its first epoch.
            "tiles_per_s": steps * self.loader.super_batch / epoch_time,
        }
        if skipped:
            # Flag the partial epoch: its loss/acc means cover only the
            # post-resume steps (the state is still exact — the skipped
            # steps were already applied before the preemption).
            record["resumed_mid_epoch_at_step"] = skipped
        # When the super-batch exceeds the dataset, an "epoch" processes each
        # tile wrap_factor times — record it so tiles_per_s cannot read as
        # dataset coverage (VERDICT r2: flagship super-batch 2048 vs 97 tiles
        # counts each tile ~21x per epoch).
        wrap = len(self.loader) * self.loader.super_batch / max(len(self.train_ds), 1)
        if wrap > 1.0 + 1e-9:
            record["wrap_fill_factor"] = round(wrap, 2)
        record.update(
            {f"t_{name}_s": t for name, t in self.timer.means().items()}
        )
        if self.perf is not None:
            # Goodput accounting from the epoch's disjoint training-thread
            # intervals: the compiled step dispatch is productive, the
            # host wait for the next super-batch is a 'data' debit
            # (loader_gather/cast/upload run on producer threads and
            # overlap the step — they are throughput, not wall debits).
            totals = self.timer.summary()
            self.perf.productive(totals.get("step", 0.0), steps)
            self.perf.debit("data", totals.get("data", 0.0))
        self.timer.reset()
        return record

    def evaluate(self) -> Dict[str, float]:
        """Held-out mIoU/accuracy/loss — the metric path the reference lacks
        (it splits a test set and never touches it, SURVEY §3.3)."""
        if len(self.test_ds) == 0:
            return {}
        # Keep the per-batch sums ON DEVICE and fetch once per evaluation:
        # the old per-batch `cm += np.asarray(...)` forced one host round
        # trip per eval batch (~114 ms each on a tunneled/remote link,
        # docs/PERF.md).  Same pattern as train_epoch's loss list: collect
        # the device arrays, one batched device_get at the end, then the
        # exact float64 accumulation happens on the host — per-batch fp32
        # confusion entries are exact (a batch holds < 2^24 pixels), and
        # no device dtype has to survive a whole evaluation's total (a
        # running uint32 would wrap past 2^32 pixels on Cityscapes-scale
        # splits; float64 is unavailable without jax x64).
        per_batch = []
        # Strip the optimizer state from the eval input: the eval steps pin
        # the state replicated, and resharding sharded Adam moments into an
        # unused argument would all-gather them once per eval batch.
        # Under zero3 the run-layout params are [N, K] chunks — gather
        # them once per evaluation (layout.full_params is the identity
        # for every other layout), not once per batch.
        eval_state = self.state.replace(
            params=self.layout.full_params(self.state), opt_state=()
        )
        for images, labels in eval_batches(
            self.test_ds,
            self.mesh,
            global_batch=self.global_micro_batch,
            data_axis=self.cfg.parallel.data_axis_name,
            space_axis=self.cfg.parallel.space_axis_name if self.spatial else None,
        ):
            self.watchdog.beat("eval")
            out = self.eval_step(eval_state, images, labels)
            per_batch.append(
                (out["confusion"], out["loss_sum"], out["pixel_count"])
            )
        # The batched fetch waits for the WHOLE evaluation's queued device
        # compute (dispatches above are async), which can dwarf the
        # step-sized stall timeout — suspend detection rather than mis-size
        # it, exactly like the checkpoint/image-dump paths.
        with self.watchdog.paused("eval_metrics_fetch"):
            per_batch = jax.device_get(per_batch)
        cm = np.zeros((self.cfg.model.num_classes,) * 2, np.float64)
        loss_sum = 0.0
        pixels = 0.0
        for conf, nll, px in per_batch:
            cm += np.asarray(conf, np.float64)
            loss_sum += float(nll)
            pixels += float(px)
        return {
            "val_loss": loss_sum / max(pixels, 1.0),
            "val_pixel_acc": float(accuracy_from_confusion(cm)),
            "val_miou": float(mean_iou(cm)),
            "val_iou_per_class": [
                round(float(v), 4) for v in np.asarray(iou_per_class(cm))
            ],
        }

    def dump_images(self, epoch: int) -> None:
        n = min(self.cfg.train.dump_images_per_epoch, len(self.test_ds))
        if n <= 0:
            return
        images = self.test_ds.images[:n]
        labels = self.test_ds.labels[:n]
        # full_params: identity except under zero3, where the run-layout
        # params are chunks the predict fn cannot apply.
        predict_state = self.state.replace(
            params=self.layout.full_params(self.state)
        )
        preds = np.asarray(self.predict(predict_state, images))
        dump_prediction_triples(
            self.workdir,
            images,
            labels,
            preds,
            self.cfg.model.num_classes,
            epoch,
            max_samples=n,
        )

    def _log_lineage(self, event: str, lin: dict, **fields) -> None:
        """Append a flat ``kind="lineage"`` record to metrics.jsonl — the
        train-side anchor obs/merge.py joins serve-side streams onto."""
        if jax.process_index() != 0:
            return
        self.logger.log(
            {
                "kind": "lineage",
                "event": event,
                **obs_lineage.flatten(lin),
                **fields,
            },
            echo=False,
        )

    def save(self, epoch: int) -> None:
        # Checkpoints store the canonical gathered layout — under a sharded
        # run layout this all-gathers the moments ONCE per save (a
        # transient; the steady state never holds them replicated), and the
        # on-disk blob restores bit-identically into either layout.  The
        # gather is a collective: every process runs it, then only process
        # 0 snapshots/writes (AsyncCheckpointer's gate).
        step = int(jax.device_get(self.state.step))
        lin = obs_lineage.make_lineage(
            step, run_id=self.run_id, config_hash_hex=self.config_hash
        )
        with self.tracer.span(
            "checkpoint_snapshot",
            epoch=epoch,
            lineage_id=lin["lineage_id"],
            step=step,
        ):
            state = self.layout.canonical(self.state)
            self.checkpointer.save(
                self.ckpt_dir,
                state,
                step=step,
                metadata={
                    "epoch": epoch,
                    "config": self.cfg.to_dict(),
                    # The predict CLI rebuilds its restore target from this —
                    # channels come from the dataset, not the config (ADVICE r1).
                    "input_channels": int(self.train_ds.image_shape[-1]),
                    "lineage": lin,
                },
            )
        self._log_lineage("checkpoint_saved", lin, epoch=epoch)
        if jax.process_index() == 0:
            # Progress breadcrumb: the supervisor resets its crash-loop
            # counter when this step advances between attempts.
            write_breadcrumb(
                self.workdir,
                "running",
                epoch=epoch,
                last_ckpt_step=int(jax.device_get(self.state.step)),
            )

    def fit(self, epochs: Optional[int] = None) -> Dict[str, float]:
        """Run the full training; returns the last epoch's metrics record."""
        cfg = self.cfg.train
        epochs = epochs if epochs is not None else cfg.epochs
        if epochs != cfg.epochs and cfg.lr_schedule != "constant":
            # The decaying schedule's horizon was built from cfg.epochs; an
            # overridden epoch budget would otherwise clamp at LR 0 past the
            # configured horizon (or end early).  Rebuild over the actual
            # horizon — the optimizer state structure is unchanged.
            self.tx = build_optimizer(
                cfg, total_steps=epochs * len(self.loader)
            )
            self.train_step = self._build_train_step()
        record: Dict[str, float] = {}
        # SIGUSR2 → arm the on-demand profiler (kill -USR2 <pid> against a
        # live run; the next profile_steps steps are captured and
        # aggregated).  Installable only from the main thread — tests and
        # embedded fits from worker threads skip the handler and use
        # /debug/trace or profiler.arm() directly.
        prev_handler = None
        sigusr2 = getattr(signal, "SIGUSR2", None)
        if sigusr2 is not None:
            try:
                prev_handler = signal.signal(
                    sigusr2, lambda signum, frame: self.profiler.arm()
                )
            except ValueError:
                pass  # not the main thread
        # SIGTERM → graceful preemption (docs/RESILIENCE.md): finish the
        # in-flight step, emergency-checkpoint, drain, exit 43.  Main
        # thread only, same constraint as SIGUSR2; embedded fits preempt
        # via request_preempt() directly.  NOTE (multi-host): the graceful
        # save runs collectives, so it is only safe when the scheduler
        # signals EVERY process — the normal preemption contract; a
        # partial signal ends in the grace-window hard exit instead.
        prev_term = None
        sigterm = getattr(signal, "SIGTERM", None)
        if sigterm is not None:
            try:
                prev_term = signal.signal(
                    sigterm, lambda signum, frame: self.request_preempt()
                )
            except ValueError:
                pass  # not the main thread
        if jax.process_index() == 0:
            write_breadcrumb(
                self.workdir, "running", start_epoch=self.start_epoch,
                epochs=epochs,
            )
        if self.perf is not None:
            self.perf.start()
        try:
            with self.watchdog:
                try:
                    for epoch in range(self.start_epoch, epochs):
                        if self._preempt.is_set():
                            # Preemption arrived between epochs (or during
                            # the post-epoch eval/checkpoint/dump phases).
                            raise PreemptedRun(epoch, 0)
                        with self.tracer.span("epoch", epoch=epoch):
                            with maybe_profile(
                                os.path.join(self.workdir, "profile"),
                                enabled=epoch == cfg.profile_epoch,
                            ):
                                record = self.train_epoch(epoch)
                        if cfg.eval_every_epochs and (epoch + 1) % cfg.eval_every_epochs == 0:
                            # evaluate() beats per batch; per-batch eval cost is
                            # step-like, so the step-sized timeout applies.
                            t_eval = time.perf_counter()
                            with self.tracer.span("evaluate", epoch=epoch):
                                record.update(self.evaluate())
                            if self.perf is not None:
                                self.perf.debit(
                                    "eval", time.perf_counter() - t_eval
                                )
                        if self._chaos is not None:
                            # nan@N fault: poison what the health detectors
                            # see (the stream logs the same poisoned value).
                            record = self._chaos.corrupt_record(record)
                        self.logger.log(record)
                        # Health detectors see exactly what the stream saw.
                        self.health.observe_train(record)
                        if cfg.checkpoint_every_epochs and (
                            epoch + 1
                        ) % cfg.checkpoint_every_epochs == 0:
                            # Snapshot/serialization time is unrelated to the
                            # step-sized timeout — suspend detection rather than
                            # mis-size it.  Under checkpoint_async this blocks
                            # only for the host snapshot (plus a barrier if the
                            # PREVIOUS write is somehow still running); the write
                            # itself overlaps the next epoch.
                            t_ckpt = time.perf_counter()
                            with self.watchdog.paused("checkpoint"):
                                self.save(epoch)
                            if self.perf is not None:
                                # The training-thread STALL (snapshot +
                                # barrier), not the background write.
                                self.perf.debit(
                                    "checkpoint", time.perf_counter() - t_ckpt
                                )
                        if self.perf is not None:
                            # Refresh ddlpc_mfu/ddlpc_goodput and append the
                            # flat kind="perf"/"comm" accounting records
                            # (scripts/perf_report.py renders these).
                            self.logger.log(
                                self.perf.publish(
                                    step_time_s=record.get("step_time_s")
                                ),
                                echo=False,
                            )
                            if self.comm is not None:
                                self.logger.log(
                                    self.comm.publish(
                                        step_time_s=record.get("step_time_s")
                                    ),
                                    echo=False,
                                )
                        if cfg.dump_images_per_epoch:
                            with self.watchdog.paused("image_dump"):
                                self.dump_images(epoch)
                    else:
                        if jax.process_index() == 0:
                            write_breadcrumb(
                                self.workdir, "done", epochs=epochs
                            )
                except PreemptedRun as p:
                    self._graceful_preempt(p.epoch, p.steps_done)
                finally:
                    # Exit barrier: fit() must not return (or unwind) with a
                    # checkpoint still in flight — this also re-raises a writer
                    # failure on the training thread.  close() additionally
                    # shuts the writer thread down (one leaked non-daemon
                    # thread per Trainer otherwise); a later save()/fit() on
                    # this Trainer transparently respawns it.
                    with self.watchdog.paused("checkpoint_flush"):
                        with self.tracer.span("checkpoint_barrier"):
                            self.checkpointer.close()
        finally:
            if prev_handler is not None:
                try:
                    signal.signal(sigusr2, prev_handler)
                except ValueError:
                    pass
            if prev_term is not None:
                try:
                    signal.signal(sigterm, prev_term)
                except ValueError:
                    pass
            # A pending grace timer must not outlive fit (it would hard-
            # exit a process that finished its graceful path long ago).
            self._preempt_done.set()
            if self._grace_timer is not None:
                self._grace_timer.cancel()
                self._grace_timer = None
            # A capture the run ended mid-way through still produces its
            # report over the steps that actually happened.
            self.profiler.finalize(
                sync=lambda: jax.block_until_ready(self.state.step)
            )
            # Traced runs drop a Perfetto-loadable trace.json in the
            # workdir at every fit() exit (flush is idempotent; the tracer
            # stays usable for a subsequent fit on this Trainer).
            self.tracer.flush()
        return record
