"""Observability: metrics logs, stage timing, qualitative image dumps.

Reference parity (SURVEY §5): per-epoch loss/accuracy/timing lines appended
to a txt file (кластер.py:715-716,781-782), wall-clock prints per sync stage
(кластер.py:116,265,317,389,397,440), and 5 (prediction, label, image) PNG
triples per epoch (кластер.py:785-790).  Here the txt log is kept (same
human-readable shape) plus a machine-readable JSONL stream, timings come
from a reusable ``StageTimer``, and the PNG dumps color classes through a
fixed palette instead of the reference's ``pred*5`` grayscale trick.
Only process 0 writes.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager
from typing import Dict, Optional

import jax
import numpy as np

from ddlpc_tpu.analysis import lockcheck
from ddlpc_tpu.obs.registry import sanitize_name
from ddlpc_tpu.obs.schema import SCHEMA_VERSION
from ddlpc_tpu.utils.fsio import atomic_write_text

# ISPRS-style 6-class palette (imp surface, building, low veg, tree, car,
# clutter) extended by hashing for datasets with more classes.
_PALETTE = np.array(
    [
        [255, 255, 255],
        [0, 0, 255],
        [0, 255, 255],
        [0, 255, 0],
        [255, 255, 0],
        [255, 0, 0],
    ],
    np.uint8,
)


def class_palette(num_classes: int) -> np.ndarray:
    if num_classes <= len(_PALETTE):
        return _PALETTE[:num_classes]
    rng = np.random.default_rng(0)
    extra = rng.integers(0, 256, size=(num_classes - len(_PALETTE), 3), dtype=np.uint8)
    return np.concatenate([_PALETTE, extra])


class MetricsLogger:
    """Append-only txt + JSONL metric streams under ``workdir``.

    txt mirrors the reference's epoch lines (кластер.py:781-782); JSONL is
    the machine-readable record new in this framework.
    """

    def __init__(
        self,
        workdir: str,
        run_config_json: Optional[str] = None,
        basename: str = "metrics",
        registry=None,
    ):
        # ``basename`` lets other subsystems share this stream format
        # without clobbering the training log (serve/metrics.py writes
        # ``serve_metrics.jsonl`` next to ``metrics.jsonl``).
        self.enabled = jax.process_index() == 0
        self.workdir = workdir
        # Optional MetricsRegistry (obs/registry.py): every numeric scalar
        # logged here is also published as a gauge so the Prometheus
        # exposition (/metrics on the telemetry endpoint) always shows the
        # latest value of everything the JSONL stream carries.
        self.registry = None
        self._records_total = None
        if registry is not None:
            self.attach_registry(registry)
        if not self.enabled:
            return
        os.makedirs(workdir, exist_ok=True)
        self.txt_path = os.path.join(workdir, f"{basename}.txt")
        self.jsonl_path = os.path.join(workdir, f"{basename}.jsonl")
        if run_config_json is not None:
            # Run-config header, as the reference writes before epoch 0
            # (кластер.py:715-716) — rename-atomic so restore tooling
            # never reads a torn config; durable=False because this runs
            # once per trainer construction and the ~50ms container fsync
            # would tax every tiny test fit for an advisory file.
            atomic_write_text(
                os.path.join(workdir, "config.json"),
                run_config_json,
                durable=False,
            )

    def attach_registry(self, registry) -> None:
        """Wire (or re-wire) a MetricsRegistry after construction — the
        serve frontend owns its registry but receives a logger built
        before it exists, and the quantile snapshots must still reach the
        Prometheus exposition."""
        self.registry = registry
        self._records_total = registry.counter(
            "ddlpc_log_records_total",
            "JSONL records written, by record kind.",
            labelnames=("kind",),
        )

    def log(self, record: Dict[str, object], echo: bool = True) -> None:
        if not self.enabled:
            return
        record = {
            k: (float(v) if isinstance(v, (np.floating, jax.Array)) else v)
            for k, v in record.items()
        }
        record.setdefault("time", time.time())
        # Every stream record carries the flat-JSONL schema version so any
        # tool (scripts/obs_tail.py, scripts/check_metrics_schema.py) can
        # tail/lint training, serving, span, and alert streams identically.
        record.setdefault("schema", SCHEMA_VERSION)
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        if self.registry is not None:
            self._publish(record)
        line = "  ".join(
            f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
            for k, v in record.items()
            if k not in ("time", "schema")
        )
        with open(self.txt_path, "a") as f:
            f.write(line + "\n")
        if echo:
            print(line, flush=True)

    def _publish(self, record: Dict[str, object]) -> None:
        """Numeric scalars → ``ddlpc_<kind>_<key>`` gauges in the registry."""
        kind = str(record.get("kind", "train"))
        self._records_total.inc(kind=kind)
        prefix = sanitize_name(f"ddlpc_{kind}")
        for k, v in record.items():
            if k in ("time", "schema", "kind"):
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self.registry.gauge(
                f"{prefix}_{sanitize_name(k)}",
                f"Latest {k!r} from the {kind} JSONL stream.",
            ).set(float(v))


@lockcheck.guarded
class StageTimer:
    """Named wall-clock stage timing — the structured form of the
    reference's scattered ``time.time()`` delta prints (кластер.py:265-440).
    Accumulates totals; ``summary()`` gives seconds per stage.

    Thread-safe: the ShardedLoader's producer pool records its
    loader_gather/cast/upload stages from worker threads concurrently with
    the training thread's data/step stages.

    ``tracer`` (obs/tracing.py, optional) additionally records every stage
    as a span — this is how the loader's per-stage hooks reach the unified
    trace without the loader knowing the tracer exists.  Stages run on
    producer threads, so spans are recorded with the tracer's explicit
    cross-thread ``add_span`` (no implicit parent)."""

    def __init__(self, tracer=None):
        self.totals: Dict[str, float] = {}  # guarded-by: _lock
        self.counts: Dict[str, int] = {}  # guarded-by: _lock
        self.tracer = tracer
        self._lock = lockcheck.lock("StageTimer._lock")

    @contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            dt = t1 - t0
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1
            tracer = self.tracer
            if tracer is not None and tracer.enabled:
                tracer.add_span(name, t0, t1)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.totals)

    def means(self) -> Dict[str, float]:
        with self._lock:
            return {
                k: self.totals[k] / max(self.counts[k], 1) for k in self.totals
            }

    def reset(self) -> None:
        with self._lock:
            self.totals.clear()
            self.counts.clear()


@contextmanager
def maybe_profile(trace_dir: Optional[str], enabled: bool = True):
    """XLA/TPU profiler trace around a block (view with TensorBoard or
    xprof).  The reference's only tracing is scattered wall-clock prints
    (SURVEY §5); this wraps ``jax.profiler.trace`` so one config flag
    captures real device timelines.  No-op when disabled or trace_dir is
    None; never fails the run if the profiler is unavailable."""
    if not enabled or not trace_dir or jax.process_index() != 0:
        yield
        return
    # Guard only the profiler's own enter/exit — an exception raised by the
    # profiled body must propagate untouched.
    import warnings

    ctx = jax.profiler.trace(trace_dir)
    try:
        ctx.__enter__()
    except Exception as e:  # profiler may be unsupported on a backend
        warnings.warn(f"profiler trace failed to start: {e}", stacklevel=2)
        yield
        return
    try:
        yield
    finally:
        try:
            ctx.__exit__(None, None, None)
        except Exception as e:
            warnings.warn(f"profiler trace failed to stop: {e}", stacklevel=2)


def dump_prediction_triples(
    workdir: str,
    images: np.ndarray,
    labels: np.ndarray,
    preds: np.ndarray,
    num_classes: int,
    epoch: int,
    max_samples: int = 5,
) -> None:
    """Write (Model i, Label i, Image i) PNG triples (кластер.py:785-790)."""
    if jax.process_index() != 0:
        return
    from PIL import Image

    out_dir = os.path.join(workdir, "images", f"epoch_{epoch:04d}")
    os.makedirs(out_dir, exist_ok=True)
    pal = class_palette(num_classes)
    n = min(max_samples, len(images))
    for i in range(n):
        pred_rgb = pal[np.clip(preds[i], 0, num_classes - 1)]
        lab_rgb = pal[np.clip(labels[i], 0, num_classes - 1)]
        img_u8 = np.clip(images[i] * 255.0, 0, 255).astype(np.uint8)
        if img_u8.shape[-1] == 1:
            img_u8 = np.repeat(img_u8, 3, axis=-1)
        Image.fromarray(pred_rgb).save(os.path.join(out_dir, f"Model {i}.png"))
        Image.fromarray(lab_rgb).save(os.path.join(out_dir, f"Label {i}.png"))
        Image.fromarray(img_u8).save(os.path.join(out_dir, f"Image {i}.png"))
