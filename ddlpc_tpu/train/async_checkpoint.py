"""Asynchronous checkpointing: take save I/O off the training critical path.

The synchronous save stalls the epoch loop for the full device_get →
chunk → compress → fsync chain; at pod scale that stall dominates
(PAPERS: "Scalable Training of Language Models using JAX pjit and
TPUv4" overlaps checkpoint I/O with compute for exactly this reason).
Here the training thread pays ONLY for the host snapshot
(checkpoint.snapshot_state — a bounded memcpy of the state, required for
correctness anyway because the next step may reuse donated buffers);
everything downstream (chunking, compression, fsync, prune) runs on one
background writer thread.

Semantics (tests/test_checkpoint_format.py):

- **Ordering barrier**: a save issued while the previous write is still
  in flight first waits for it — checkpoints hit disk strictly in issue
  order and at most one write is ever in flight (bounded memory: one
  snapshot).
- **Exceptions surface on the training thread**: a writer failure (disk
  full, permission) is re-raised by the next ``save()`` or ``wait()``,
  never swallowed — training must not run for hours believing it is
  checkpointed.
- **Exit barrier**: callers must ``wait()`` (or use the context manager)
  before treating the run as checkpointed; ``Trainer.fit`` barriers
  after the epoch loop, inside ``watchdog.paused`` (write time is
  unrelated to the step-sized stall timeout).
- **Process gate**: non-zero processes no-op on ``save`` (state is
  replicated; only process 0 writes), matching ``save_checkpoint``.
- **Canonical layout in**: callers pass the gathered (replicated) state —
  under the ZeRO-sharded update ``Trainer.save`` first runs
  ``StateLayout.canonical`` (a collective all processes join), so the
  snapshot below never sees chunked moments and on-disk blobs stay
  layout-independent (docs/SHARDING.md); ``snapshot_state`` rejects
  non-addressable leaves with a pointer to that contract.
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Optional

import jax

from ddlpc_tpu.analysis import lockcheck
from ddlpc_tpu.train import checkpoint as ckpt


@lockcheck.guarded
class AsyncCheckpointer:
    """Background-threaded ``save_checkpoint`` with sync fallback.

    ``background=False`` runs the identical write inline (same format,
    same snapshot path) — the knob ``TrainConfig.checkpoint_async`` maps
    here, so an A/B between the modes differs only in WHERE the write
    runs, never in what lands on disk.

    Concurrency design: there is deliberately NO lock — ``save``/``wait``
    /``close`` are single-writer (the training thread), the future is the
    hand-off, and the ``wait()`` barrier orders every cross-thread read.
    The ``# guarded-by: <owner-thread>`` annotations pin that shape:
    under ``DDLPC_LOCKCHECK=1`` a second mutating thread is a violation,
    not a silent race.  ``last_write_s`` is the one writer-thread field —
    it is written before the future resolves and only read after the
    ``wait()`` barrier, so it carries no annotation.
    """

    def __init__(
        self,
        keep: int = 3,
        format: str = "chunked",
        chunk_bytes: int = ckpt.CHUNK_BYTES,
        compression: str = "adaptive",
        background: bool = True,
    ):
        self.keep = keep
        self.format = format
        self.chunk_bytes = chunk_bytes
        self.compression = compression
        self.background = background
        self._pool = None  # guarded-by: <owner-thread>
        self._inflight = None  # guarded-by: <owner-thread>
        # Observability: what the TRAINING thread paid for the last save
        # (snapshot + any barrier on the previous write) vs what the write
        # actually cost in the background.
        self.last_stall_s = 0.0  # guarded-by: <owner-thread>
        self.last_write_s = 0.0
        self.saves = 0  # guarded-by: <owner-thread>

    def _executor(self) -> concurrent.futures.ThreadPoolExecutor:
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                1, thread_name_prefix="ckpt-writer"
            )
        return self._pool

    # -- core ---------------------------------------------------------------

    def save(
        self,
        ckpt_dir: str,
        state,
        step: int,
        metadata: Optional[dict] = None,
    ) -> None:
        """Snapshot ``state`` and schedule (or perform) the write.

        Blocks only for the host snapshot — plus, if the previous write
        is still running, a barrier on it (which also re-raises its
        failure here, on the training thread).

        ``metadata`` (including the trainer's lineage record) passes
        through to ``save_snapshot`` untouched; lineage ``saved_at`` is
        restamped THERE, on the writer thread at the durable-write
        moment — under a backlogged async writer the trainer-side stamp
        can be arbitrarily stale, and the freshness/deploy-latency
        gauges must anchor on when bytes actually hit disk.
        """
        t0 = time.perf_counter()
        self.wait()
        if jax.process_index() != 0:
            self.last_stall_s = time.perf_counter() - t0
            return
        snap = ckpt.snapshot_state(state)

        def write():
            w0 = time.perf_counter()
            ckpt.save_snapshot(
                ckpt_dir,
                snap,
                step,
                metadata=metadata,
                keep=self.keep,
                format=self.format,
                chunk_bytes=self.chunk_bytes,
                compression=self.compression,
            )
            self.last_write_s = time.perf_counter() - w0

        if self.background:
            self._inflight = self._executor().submit(write)
        else:
            write()
        self.saves += 1
        self.last_stall_s = time.perf_counter() - t0

    def wait(self) -> None:
        """Barrier on the in-flight write; re-raises its exception here."""
        inflight, self._inflight = self._inflight, None
        if inflight is not None:
            inflight.result()

    @property
    def in_flight(self) -> bool:
        return self._inflight is not None and not self._inflight.done()

    def close(self) -> None:
        """Final barrier + writer-thread shutdown (idempotent)."""
        try:
            self.wait()
        finally:
            if self._pool is not None:
                self._pool.shutdown(wait=True)
                self._pool = None

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
