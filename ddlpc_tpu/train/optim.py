"""Optimizer construction from TrainConfig.

The reference uses ``torch.optim.Adam`` at default LR on every replica
(кластер.py:704); state reaches workers via the init-time pickle broadcast
(кластер.py:560-565).  Here optimizer state is part of the replicated
TrainState pytree.
"""

from __future__ import annotations

import optax

from ddlpc_tpu.config import TrainConfig


def build_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    if cfg.optimizer == "adam":
        tx = optax.adam(cfg.learning_rate)
    elif cfg.optimizer == "adamw":
        tx = optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "sgd":
        tx = optax.sgd(cfg.learning_rate, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.weight_decay and cfg.optimizer == "adam":
        tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    return tx
