"""Optimizer construction from TrainConfig.

The reference uses ``torch.optim.Adam`` at default LR on every replica
(кластер.py:704); state reaches workers via the init-time pickle broadcast
(кластер.py:560-565).  Here optimizer state is part of the replicated
TrainState pytree.
"""

from __future__ import annotations

from typing import Optional, Union

import optax

from ddlpc_tpu.config import TrainConfig


def build_schedule(
    cfg: TrainConfig, total_steps: Optional[int] = None
) -> Union[float, optax.Schedule]:
    """LR schedule from config.  ``total_steps`` is the run's optimizer-step
    horizon (epochs × steps/epoch), required for decaying schedules."""
    if cfg.lr_schedule == "constant":
        if cfg.warmup_steps:
            return optax.linear_schedule(
                0.0, cfg.learning_rate, cfg.warmup_steps
            )
        return cfg.learning_rate
    if cfg.lr_schedule == "cosine":
        if not total_steps or total_steps <= 0:
            raise ValueError(
                "lr_schedule='cosine' needs the run's total step count; "
                "construct through the Trainer or pass total_steps"
            )
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=min(cfg.warmup_steps, max(total_steps - 1, 0)),
            decay_steps=total_steps,
        )
    raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")


def build_optimizer(
    cfg: TrainConfig, total_steps: Optional[int] = None
) -> optax.GradientTransformation:
    lr = build_schedule(cfg, total_steps)
    if cfg.optimizer == "adam":
        tx = optax.adam(lr)
    elif cfg.optimizer == "adamw":
        tx = optax.adamw(lr, weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "sgd":
        tx = optax.sgd(lr, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.weight_decay and cfg.optimizer == "adam":
        tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
    if cfg.grad_clip_norm:
        if cfg.grad_clip_norm < 0:
            raise ValueError(
                f"grad_clip_norm must be >= 0, got {cfg.grad_clip_norm}"
            )
        # Clip first, then the optimizer sees bounded gradients.  This runs
        # inside the compiled step after sync_gradients, so the global norm
        # is of the already-averaged (and codec-processed) gradient.
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), tx)
    return tx
