"""U-Net for semantic segmentation, NHWC / bf16, Flax.

Reference parity: UNet with 5 down blocks (3→64/N→128/N→256/N→512/N→512/N),
a DoubleConv(512/N) bottleneck, 5 up blocks and a final 1×1 conv to
``out_classes`` logits, with ``up_sample_mode`` ∈ {conv_transpose, bilinear}
and global width divisor N = ``NN_in_model`` (кластер.py:620-656,687).

Differences (deliberate, TPU-first): NHWC layout, bf16 compute with fp32
params, pluggable/synced normalization, arbitrary depth via ``features``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ddlpc_tpu.models.layers import (
    DetailHead,
    DoubleConv,
    DownBlock,
    StemGridDetailHead,
    UpBlock,
    apply_stem,
    head_channels,
    restore_head,
)


class UNet(nn.Module):
    num_classes: int = 6
    features: Tuple[int, ...] = (64, 128, 256, 512, 512)
    bottleneck_features: int = 512
    width_divisor: int = 1
    up_sample_mode: str = "conv_transpose"
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    stem: str = "none"  # none | s2d (see ModelConfig.stem)
    stem_factor: int = 2
    # Residual refinement after the subpixel head — restores
    # sub-stem_factor-px structure the 1/r pyramid cannot carry.  Kind
    # selects the architecture: 'fullres' = DetailHead (two full-res convs),
    # 's2d' = StemGridDetailHead (same idea computed at the stem grid on
    # MXU-shaped channels) — see ModelConfig.detail_head_kind.
    detail_head: bool = False
    detail_head_kind: str = "fullres"  # fullres | s2d
    detail_head_hidden: int = 16
    # 'grouped': under train=True with an s2d stem, return pre-d2s
    # phase-major logits [B,H/r,W/r,r²·C] instead of full-res — the train
    # step pairs them with group_labels for identical loss math without any
    # full-res tensor (ModelConfig.train_head_layout).  Eval/predict
    # (train=False) always return full-res logits.
    train_head_layout: str = "fullres"  # fullres | grouped
    dtype: Any = jnp.bfloat16
    head_dtype: Any = jnp.float32  # see ModelConfig.head_dtype

    def _w(self, f: int) -> int:
        return max(1, f // self.width_divisor)

    # -- pipeline staging (parallel/pipeline.py, docs/SHARDING.md) --------
    # The encoder–decoder as an ordered list of cut-point blocks.  Names
    # equal the flax auto-names the parameter tree has always used (the
    # explicit ``name=`` kwargs below pin them call-order-independent), so
    # stage rule tables over param paths and the execution slice agree by
    # construction and checkpoints are unaffected.

    def pipeline_block_names(self) -> Tuple[str, ...]:
        k = len(self.features)
        names = [f"DownBlock_{i}" for i in range(k)] + ["DoubleConv_0"]
        for i in range(k):
            # Each UpBlock splits into two cut points — the decoder's
            # DoubleConvs are the heaviest modules in the tree, and a
            # balanced 2-stage cut needs to land between upsample+concat
            # and the convs (UpBlock ``phase``, models/layers.py).
            names += [f"UpBlock_{i}:up", f"UpBlock_{i}:conv"]
        return tuple(names + ["head"])

    def pipeline_block_modules(self) -> dict:
        """Block name → the param-tree module paths ("/"-joined) it owns
        (the stage rule table covers params by these)."""
        out: dict = {}
        for b in self.pipeline_block_names():
            if b == "head":
                head = ["Conv_0"]
                if self.detail_head and self.detail_head_kind == "s2d":
                    head.append("StemGridDetailHead_0")
                if self.detail_head and self.detail_head_kind == "fullres":
                    head.append("DetailHead_0")
                out[b] = tuple(head)
            elif b.endswith(":up"):
                out[b] = (b[: -len(":up")] + "/ConvTranspose_0",)
            elif b.endswith(":conv"):
                out[b] = (b[: -len(":conv")] + "/DoubleConv_0",)
            else:
                out[b] = (b,)
        return out

    def carry_has_image(self) -> bool:
        """Whether the inter-stage carry must ship the raw full-res input
        forward (only the detail heads consume it at the tail)."""
        return bool(self.detail_head)

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        train: bool = True,
        blocks: Optional[Sequence[str]] = None,
        carry: Optional[dict] = None,
    ):
        """x: [N, H, W, C] float, H and W divisible by
        2**len(features) (× ``stem_factor`` with the s2d stem); returns
        logits [N, H, W, num_classes] in ``head_dtype`` (float32 default).

        Staged execution (``parallel/pipeline.py``): ``blocks`` names a
        contiguous slice of :meth:`pipeline_block_names` to run.  The first
        stage (``carry=None``) consumes the raw image; later stages resume
        from the ``carry`` dict the previous stage returned (``x`` is
        ignored then).  A slice that does not end in ``'head'`` returns the
        carry ``{'x', 'skips'[, 'image']}`` instead of logits — every leaf
        stays in ``self.dtype``, so no dtype widening crosses a stage
        boundary (the program auditor's per-stage contract pins this).
        ``blocks=None`` (default) runs everything — byte- and
        program-identical to the unstaged revisions."""
        names = self.pipeline_block_names()
        if blocks is None:
            blocks = names
        else:
            blocks = tuple(blocks)
            lo = names.index(blocks[0])
            if blocks != names[lo : lo + len(blocks)]:
                raise ValueError(
                    f"blocks {blocks} is not a contiguous slice of the "
                    f"pipeline block order {names}"
                )
            if (carry is None) != (lo == 0):
                raise ValueError(
                    "the first stage (and only it) starts from the raw "
                    "image: pass carry=None exactly when blocks starts at "
                    f"{names[0]!r}"
                )
        if carry is None:
            x = x.astype(self.dtype)
            image = x  # raw full-res input, kept for the optional DetailHead
            # s2d: run the whole pyramid at 1/r resolution on r²-richer
            # channels; logits return to full resolution via a subpixel head.
            x = apply_stem(x, self.stem, self.stem_factor)
            min_px = 2 ** len(self.features)
            if x.shape[1] < min_px or x.shape[2] < min_px:
                # A too-shallow input silently pools to a ZERO-size tensor at
                # the deepest level, and BatchNorm over 0 elements is NaN that
                # the codec's global max-abs then spreads to every gradient —
                # fail loudly instead (found the hard way on a 64² smoke run).
                raise ValueError(
                    f"input {image.shape[1:3]} too small for a "
                    f"{len(self.features)}-level pyramid behind the "
                    f"{self.stem!r} stem (grid {x.shape[1:3]} after the stem; "
                    f"the deepest pool needs ≥ {min_px} px) — use a larger "
                    f"tile, fewer features, or a smaller stem_factor"
                )
            skips = []
        else:
            x = carry["x"]
            skips = list(carry["skips"])
            image = carry.get("image")
        common = dict(
            norm=self.norm,
            norm_axis_name=self.norm_axis_name,
            norm_groups=self.norm_groups,
            dtype=self.dtype,
        )
        k = len(self.features)
        i = 0
        while i < len(blocks):
            b = blocks[i]
            if b.startswith("DownBlock_"):
                f = self.features[int(b.rsplit("_", 1)[1])]
                x, skip = DownBlock(self._w(f), name=b, **common)(x, train)
                skips.append(skip)
            elif b == "DoubleConv_0":
                x = DoubleConv(
                    self._w(self.bottleneck_features), name=b, **common
                )(x, train)
            elif b.startswith("UpBlock_"):
                base, phase = b.split(":")
                f = self.features[k - 1 - int(base.rsplit("_", 1)[1])]
                up = UpBlock(
                    self._w(f),
                    up_sample_mode=self.up_sample_mode,
                    name=base,
                    **common,
                )
                if phase == "up" and i + 1 < len(blocks):
                    # Both halves in this slice: one call (the unstaged
                    # program, byte-identical to pre-phase revisions).
                    x = up(x, skips.pop(), train)
                    i += 2
                    continue
                if phase == "up":
                    x = up(x, skips.pop(), train, phase="up")
                else:  # the cut landed inside this UpBlock
                    x = up(x, (), train, phase="conv")
            elif b == "head":
                return self._head(x, image, train)
            else:  # pragma: no cover - guarded by the slice check above
                raise ValueError(f"unknown pipeline block {b!r}")
            i += 1
        out = {"x": x, "skips": tuple(skips)}
        if self.carry_has_image():
            out["image"] = image
        return out

    def _head(self, x: jax.Array, image: Optional[jax.Array], train: bool):
        """The 1×1 logit conv + optional detail refinement — the atomic
        last pipeline block (submodule creation from a helper is fine: the
        compact context of ``__call__`` is active)."""
        z = nn.Conv(
            head_channels(self.num_classes, self.stem, self.stem_factor),
            (1, 1),
            dtype=self.head_dtype,
            param_dtype=jnp.float32,
            name="Conv_0",
        )(x.astype(self.head_dtype))
        if self.detail_head and self.detail_head_kind == "s2d":
            if self.stem != "s2d":
                raise ValueError(
                    "detail_head_kind='s2d' refines the pre-d2s logit grid — "
                    "it requires stem='s2d' (with stem='none' there is no "
                    "stem grid; use detail_head_kind='fullres')"
                )
            z = StemGridDetailHead(
                self.num_classes,
                self.stem_factor,
                hidden=self.detail_head_hidden,
                dtype=self.dtype,
                head_dtype=self.head_dtype,
                name="StemGridDetailHead_0",
            )(z, image)
        if (
            train
            and self.train_head_layout == "grouped"
            and self.stem == "s2d"
            and not (self.detail_head and self.detail_head_kind == "fullres")
        ):
            # Phase-major grouped logits: d2s is a pure layout permutation,
            # so the grouped loss path skips it entirely (train_head_layout).
            return z
        logits = restore_head(z, self.stem, self.stem_factor)
        if self.detail_head and self.detail_head_kind == "fullres":
            logits = DetailHead(
                self.num_classes,
                hidden=self.detail_head_hidden,
                dtype=self.dtype,
                head_dtype=self.head_dtype,
                name="DetailHead_0",
            )(logits, image)
        return logits
