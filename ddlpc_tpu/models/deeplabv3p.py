"""DeepLabV3+ — atrous (dilated) convolutions + ASPP + light decoder.

Required by BASELINE.json config 4 ("DeepLabV3+ / Potsdam 512×512, atrous
conv, larger activations"); absent from the reference (plain U-Net only,
кластер.py:620-656).  TPU-first choices: NHWC throughout, bf16 compute with
fp32 params, residual encoder with stride-16 output (last stage dilated
instead of strided, Chen et al. 2018), global pooling branch broadcast back
to the feature map, all upsampling via bilinear resize (static shapes).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ddlpc_tpu.models.layers import ConvNormAct, Norm


class ResidualBlock(nn.Module):
    """Two 3×3 convs with a projection shortcut when shape changes."""

    features: int
    stride: int = 1
    dilation: int = 1
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        norm_kw = dict(
            kind=self.norm,
            axis_name=self.norm_axis_name,
            groups=self.norm_groups,
            dtype=self.dtype,
        )
        shortcut = x
        y = nn.Conv(
            self.features,
            (3, 3),
            strides=(self.stride, self.stride),
            kernel_dilation=(self.dilation, self.dilation),
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)
        y = nn.relu(Norm(**norm_kw)(y, train))
        y = nn.Conv(
            self.features,
            (3, 3),
            kernel_dilation=(self.dilation, self.dilation),
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(y)
        y = Norm(**norm_kw)(y, train)
        if shortcut.shape[-1] != self.features or self.stride != 1:
            shortcut = nn.Conv(
                self.features,
                (1, 1),
                strides=(self.stride, self.stride),
                use_bias=False,
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )(shortcut)
            shortcut = Norm(**norm_kw)(shortcut, train)
        return nn.relu(y + shortcut)


class ASPP(nn.Module):
    """Atrous Spatial Pyramid Pooling: 1×1 + dilated 3×3 branches + global
    pooling, fused by a 1×1 conv."""

    features: int = 256
    rates: Sequence[int] = (6, 12, 18)
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        common = dict(
            norm=self.norm,
            norm_axis_name=self.norm_axis_name,
            norm_groups=self.norm_groups,
            dtype=self.dtype,
        )
        branches = [
            ConvNormAct(self.features, kernel_size=(1, 1), **common)(x, train)
        ]
        for rate in self.rates:
            branches.append(
                ConvNormAct(self.features, dilation=rate, **common)(x, train)
            )
        # Image-level pooling branch: global mean → 1×1 conv → broadcast.
        pooled = x.mean(axis=(1, 2), keepdims=True)
        pooled = ConvNormAct(self.features, kernel_size=(1, 1), **common)(
            pooled, train
        )
        branches.append(
            jnp.broadcast_to(pooled, (*x.shape[:3], self.features)).astype(
                self.dtype
            )
        )
        y = jnp.concatenate(branches, axis=-1)
        return ConvNormAct(self.features, kernel_size=(1, 1), **common)(y, train)


def _resize_to(x: jax.Array, hw: Tuple[int, int]) -> jax.Array:
    n, _, _, c = x.shape
    return jax.image.resize(x, (n, *hw, c), method="bilinear").astype(x.dtype)


class DeepLabV3Plus(nn.Module):
    num_classes: int = 6
    # Encoder stage widths (stem + 4 stages).
    features: Tuple[int, ...] = (64, 128, 256, 512)
    stem_features: int = 64
    blocks_per_stage: int = 2
    width_divisor: int = 1
    output_stride: int = 16  # 16 (dilate last stage) or 8 (last two)
    aspp_features: int = 256
    aspp_rates: Sequence[int] = (6, 12, 18)
    decoder_low_level_features: int = 48
    decoder_features: int = 256
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    dtype: Any = jnp.bfloat16
    head_dtype: Any = jnp.float32  # see ModelConfig.head_dtype

    def _w(self, f: int) -> int:
        return max(1, f // self.width_divisor)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        """x: [N,H,W,C], H and W divisible by output_stride.
        Returns logits [N,H,W,num_classes] float32."""
        if self.output_stride not in (8, 16):
            raise ValueError(f"output_stride must be 8 or 16, got {self.output_stride}")
        x = x.astype(self.dtype)
        in_hw = x.shape[1:3]
        common = dict(
            norm=self.norm,
            norm_axis_name=self.norm_axis_name,
            norm_groups=self.norm_groups,
            dtype=self.dtype,
        )
        # Stem: stride-2 conv + stride-2 pool → stride 4, the canonical
        # ResNet entry (He et al. 2016).  The conv itself is strided so no
        # C=64 activation ever exists at full input resolution — a stride-1
        # stem at 512² cost ~36% of the whole train step on v5e (the conv,
        # its BatchNorm reductions, and a select-and-scatter pool-backward
        # over [B,512,512,64] dominated the trace; docs/PERF.md finding 4).
        y = ConvNormAct(self._w(self.stem_features), strides=(2, 2), **common)(
            x, train
        )
        y = nn.max_pool(y, (3, 3), strides=(2, 2), padding="SAME")
        low_level = None
        # Stage strides for output_stride 16: (1, 2, 2→dilated); for 8 the
        # last two stages are dilated.
        stage_cfg = []
        stride_so_far = 4
        dilation = 1
        for f in self.features:
            if stride_so_far >= self.output_stride:
                dilation *= 2
                stage_cfg.append((f, 1, dilation))
            else:
                stride = 1 if not stage_cfg else 2
                stride_so_far *= stride
                stage_cfg.append((f, stride, 1))
        for s, (f, stride, dil) in enumerate(stage_cfg):
            for b in range(self.blocks_per_stage):
                y = ResidualBlock(
                    self._w(f),
                    stride=stride if b == 0 else 1,
                    dilation=dil,
                    name=f"stage{s}_block{b}",
                    **common,
                )(y, train)
            if s == 0:
                low_level = y  # stride-4 features for the decoder
        y = ASPP(
            self._w(self.aspp_features),
            rates=self.aspp_rates,
            **common,
        )(y, train)
        # Decoder: ×(output_stride/4) up to the low-level grid, concat, fuse.
        y = _resize_to(y, low_level.shape[1:3])
        ll = ConvNormAct(
            self._w(self.decoder_low_level_features), kernel_size=(1, 1), **common
        )(low_level, train)
        y = jnp.concatenate([y, ll], axis=-1)
        y = ConvNormAct(self._w(self.decoder_features), **common)(y, train)
        y = ConvNormAct(self._w(self.decoder_features), **common)(y, train)
        logits = nn.Conv(
            self.num_classes, (1, 1), dtype=self.head_dtype, param_dtype=jnp.float32
        )(y.astype(self.head_dtype))
        return _resize_to(logits, in_hw)
