"""U-Net++ (nested U-Net with dense skips and deep supervision).

Required by BASELINE.json config 3 ("U-Net++ / Vaihingen, deep-supervision
decoder, stresses conv fusion"); absent from the reference, whose only model
is plain U-Net (кластер.py:620-656).  Shares the reference-parity building
blocks (DoubleConv/max-pool/UpBlock, models/layers.py) so width_divisor,
norm selection and up-sample mode behave identically across the zoo.

Architecture (Zhou et al. 2018): node X[i][j] at depth i receives the
concatenation of all same-depth predecessors X[i][0..j-1] plus the upsampled
X[i+1][j-1].  With deep supervision each X[0][j], j≥1 gets a 1×1 logit head.
Training returns the stacked per-head logits [J, N, H, W, C] so the loss is
the average of per-head cross-entropies (the paper's formulation — averaging
logits before one softmax would couple the heads' gradients); inference
returns the mean of the heads' logits (standard ensemble readout, and any
head prefix can be pruned).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ddlpc_tpu.models.layers import (
    DetailHead,
    DoubleConv,
    StemGridDetailHead,
    UpBlock,
    apply_stem,
    head_channels,
    max_pool_2x2,
    restore_head,
)


class UNetPP(nn.Module):
    num_classes: int = 6
    features: Tuple[int, ...] = (32, 64, 128, 256, 512)
    width_divisor: int = 1
    up_sample_mode: str = "conv_transpose"
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    deep_supervision: bool = True
    # TPU-first s2d stem, same trade as UNet's (layers.py:space_to_depth):
    # the dense X[0][j] row — the grid's most expensive nodes — runs at
    # 1/r² the pixels on r²-richer channels, and every supervision head
    # becomes a subpixel head.  'none' is the paper-layout default.
    stem: str = "none"  # none | s2d
    stem_factor: int = 2
    # One SHARED refinement head (DetailHead or StemGridDetailHead per
    # ``detail_head_kind``) — sharing is a PARAMETER economy (one module,
    # consistent refinement across heads).  ``detail_head_scope``:
    # 'per_head' runs the refinement compute once per supervision head
    # (depth-1 times per step — measured −43% throughput on the s2d×4 zoo
    # row, 678 → 383 tiles/s/chip at B=96); 'ensemble' refines ONLY the
    # ensemble-mean readout, which joins the deep-supervision loss as one
    # extra supervised output and is exactly what inference returns.
    detail_head: bool = False
    detail_head_kind: str = "fullres"  # fullres | s2d
    detail_head_hidden: int = 16
    detail_head_scope: str = "per_head"  # per_head | ensemble
    # See UNet.train_head_layout / ModelConfig.train_head_layout.
    train_head_layout: str = "fullres"  # fullres | grouped
    dtype: Any = jnp.bfloat16
    head_dtype: Any = jnp.float32  # see ModelConfig.head_dtype

    def _w(self, f: int) -> int:
        return max(1, f // self.width_divisor)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        """x: [N,H,W,C] float; H, W divisible by 2**(len(features)-1)
        (× ``stem_factor`` with the s2d stem).

        Returns logits in ``head_dtype`` (float32 by default):
        [N,H,W,num_classes] — except with deep
        supervision under ``train=True``, where the stacked per-head logits
        [J,N,H,W,num_classes] come back so the loss averages per-head
        cross-entropies (losses broadcast labels over leading axes, so
        ``softmax_cross_entropy(stacked, labels)`` IS the mean of the
        per-head losses)."""
        x = x.astype(self.dtype)
        image = x  # raw full-res input for the optional DetailHead
        x = apply_stem(x, self.stem, self.stem_factor)
        depth = len(self.features)
        min_px = 2 ** (depth - 1)
        if x.shape[1] < min_px or x.shape[2] < min_px:
            # Same zero-size-pool NaN hazard as UNet (see unet.py).
            raise ValueError(
                f"input {image.shape[1:3]} too small for a {depth}-level "
                f"U-Net++ grid behind the {self.stem!r} stem (grid "
                f"{x.shape[1:3]} after the stem; the deepest pool needs "
                f"≥ {min_px} px)"
            )
        common = dict(
            norm=self.norm,
            norm_axis_name=self.norm_axis_name,
            norm_groups=self.norm_groups,
            dtype=self.dtype,
        )
        # Encoder backbone: X[i][0].
        grid: dict[tuple[int, int], jax.Array] = {}
        h = x
        for i, f in enumerate(self.features):
            h_out = DoubleConv(self._w(f), name=f"x{i}_0", **common)(h, train)
            grid[(i, 0)] = h_out
            if i < depth - 1:
                h = max_pool_2x2(h_out)
        # Nested decoder: X[i][j] = Up(X[i+1][j-1]) ++ X[i][0..j-1].
        for j in range(1, depth):
            for i in range(depth - j):
                skips = [grid[(i, k)] for k in range(j)]
                grid[(i, j)] = UpBlock(
                    self._w(self.features[i]),
                    up_sample_mode=self.up_sample_mode,
                    name=f"x{i}_{j}",
                    **common,
                )(grid[(i + 1, j - 1)], skips, train)

        # Shared refinement module (parameter economy across heads); the
        # kind decides which grid it runs on (see ModelConfig).
        s2d_refine = px_refine = None
        if self.detail_head:
            if self.detail_head_kind == "s2d":
                if self.stem != "s2d":
                    raise ValueError(
                        "detail_head_kind='s2d' requires stem='s2d' "
                        "(see ModelConfig.detail_head_kind)"
                    )
                s2d_refine = StemGridDetailHead(
                    self.num_classes,
                    self.stem_factor,
                    hidden=self.detail_head_hidden,
                    dtype=self.dtype,
                    head_dtype=self.head_dtype,
                    name="detail_head",
                )
            else:
                px_refine = DetailHead(
                    self.num_classes,
                    hidden=self.detail_head_hidden,
                    dtype=self.dtype,
                    head_dtype=self.head_dtype,
                    name="detail_head",
                )
        # With a single head there is no ensemble to refine separately —
        # scope='ensemble' degenerates to per_head.
        ensemble_scope = (
            self.detail_head
            and self.detail_head_scope == "ensemble"
            and self.deep_supervision
        )

        def head_z(h: jax.Array, name: str) -> jax.Array:
            """Pre-restore (stem-grid) logits of one supervision head."""
            return nn.Conv(
                head_channels(self.num_classes, self.stem, self.stem_factor),
                (1, 1),
                dtype=self.head_dtype,
                param_dtype=jnp.float32,
                name=name,
            )(h.astype(self.head_dtype))

        def to_pixel(z: jax.Array, refine: bool) -> jax.Array:
            logits = restore_head(z, self.stem, self.stem_factor)
            if refine and px_refine is not None:
                logits = px_refine(logits, image)
            return logits

        if self.deep_supervision:
            zs = [head_z(grid[(0, j)], f"head_{j}") for j in range(1, depth)]
        else:
            zs = [head_z(grid[(0, depth - 1)], "head")]

        if s2d_refine is not None and not ensemble_scope:
            zs = [s2d_refine(z, image) for z in zs]

        # scope='ensemble': ONE refinement pass on the ensemble-mean readout
        # (the exact logits inference returns); under train it joins the
        # stacked outputs as one extra supervised term of the mean loss.
        ens_z = ens_px = None
        if ensemble_scope:
            ens = (
                jnp.mean(jnp.stack(zs).astype(jnp.float32), axis=0).astype(
                    self.head_dtype
                )
                if len(zs) > 1
                else zs[0]
            )
            if s2d_refine is not None:
                ens_z = s2d_refine(ens, image)
            else:
                ens_px = px_refine(
                    restore_head(ens, self.stem, self.stem_factor), image
                )

        grouped = (
            train
            and self.train_head_layout == "grouped"
            and self.stem == "s2d"
            and px_refine is None
        )
        if train:
            if grouped:
                outs = zs + ([ens_z] if ens_z is not None else [])
            else:
                outs = [to_pixel(z, refine=not ensemble_scope) for z in zs]
                if ens_z is not None:
                    outs.append(restore_head(ens_z, self.stem, self.stem_factor))
                elif ens_px is not None:
                    outs.append(ens_px)
            # Deep supervision always returns the stacked per-head logits
            # (loss = mean of per-head CEs via label broadcasting).
            return jnp.stack(outs) if self.deep_supervision else outs[0]

        # Inference: the ensemble readout.
        if ens_z is not None:
            return restore_head(ens_z, self.stem, self.stem_factor)
        if ens_px is not None:
            return ens_px
        if px_refine is None:
            # depth_to_space is a pure permutation, so the ensemble mean
            # commutes with it: average at the stem grid (fp32) and restore
            # ONCE instead of materializing J full-res tensors.
            z = (
                zs[0]
                if len(zs) == 1
                else jnp.mean(jnp.stack(zs).astype(jnp.float32), axis=0)
            )
            return restore_head(z, self.stem, self.stem_factor)
        logits = [to_pixel(z, refine=True) for z in zs]
        if len(logits) == 1:
            return logits[0]
        # Ensemble-mean readout in fp32 regardless of head storage dtype.
        return jnp.mean(jnp.stack(logits).astype(jnp.float32), axis=0)
