"""U-Net++ (nested U-Net with dense skips and deep supervision).

Required by BASELINE.json config 3 ("U-Net++ / Vaihingen, deep-supervision
decoder, stresses conv fusion"); absent from the reference, whose only model
is plain U-Net (кластер.py:620-656).  Shares the reference-parity building
blocks (DoubleConv/max-pool/UpBlock, models/layers.py) so width_divisor,
norm selection and up-sample mode behave identically across the zoo.

Architecture (Zhou et al. 2018): node X[i][j] at depth i receives the
concatenation of all same-depth predecessors X[i][0..j-1] plus the upsampled
X[i+1][j-1].  With deep supervision each X[0][j], j≥1 gets a 1×1 logit head.
Training returns the stacked per-head logits [J, N, H, W, C] so the loss is
the average of per-head cross-entropies (the paper's formulation — averaging
logits before one softmax would couple the heads' gradients); inference
returns the mean of the heads' logits (standard ensemble readout, and any
head prefix can be pruned).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ddlpc_tpu.models.layers import (
    DetailHead,
    DoubleConv,
    UpBlock,
    apply_stem,
    head_channels,
    max_pool_2x2,
    restore_head,
)


class UNetPP(nn.Module):
    num_classes: int = 6
    features: Tuple[int, ...] = (32, 64, 128, 256, 512)
    width_divisor: int = 1
    up_sample_mode: str = "conv_transpose"
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    deep_supervision: bool = True
    # TPU-first s2d stem, same trade as UNet's (layers.py:space_to_depth):
    # the dense X[0][j] row — the grid's most expensive nodes — runs at
    # 1/r² the pixels on r²-richer channels, and every supervision head
    # becomes a subpixel head.  'none' is the paper-layout default.
    stem: str = "none"  # none | s2d
    stem_factor: int = 2
    # One SHARED full-res DetailHead refines every supervision head's
    # logits — sharing is a PARAMETER economy (one module, consistent
    # refinement across heads); the refinement COMPUTE still runs once per
    # supervision head (depth-1 times per step), measured −43% throughput
    # on the s2d×4 zoo row (678 → 383 tiles/s/chip at B=96).  Opt-in for
    # fine-structure tasks; see ModelConfig.detail_head / UNet.
    detail_head: bool = False
    dtype: Any = jnp.bfloat16
    head_dtype: Any = jnp.float32  # see ModelConfig.head_dtype

    def _w(self, f: int) -> int:
        return max(1, f // self.width_divisor)

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        """x: [N,H,W,C] float; H, W divisible by 2**(len(features)-1)
        (× ``stem_factor`` with the s2d stem).

        Returns logits in ``head_dtype`` (float32 by default):
        [N,H,W,num_classes] — except with deep
        supervision under ``train=True``, where the stacked per-head logits
        [J,N,H,W,num_classes] come back so the loss averages per-head
        cross-entropies (losses broadcast labels over leading axes, so
        ``softmax_cross_entropy(stacked, labels)`` IS the mean of the
        per-head losses)."""
        x = x.astype(self.dtype)
        image = x  # raw full-res input for the optional DetailHead
        x = apply_stem(x, self.stem, self.stem_factor)
        depth = len(self.features)
        common = dict(
            norm=self.norm,
            norm_axis_name=self.norm_axis_name,
            norm_groups=self.norm_groups,
            dtype=self.dtype,
        )
        # Encoder backbone: X[i][0].
        grid: dict[tuple[int, int], jax.Array] = {}
        h = x
        for i, f in enumerate(self.features):
            h_out = DoubleConv(self._w(f), name=f"x{i}_0", **common)(h, train)
            grid[(i, 0)] = h_out
            if i < depth - 1:
                h = max_pool_2x2(h_out)
        # Nested decoder: X[i][j] = Up(X[i+1][j-1]) ++ X[i][0..j-1].
        for j in range(1, depth):
            for i in range(depth - j):
                skips = [grid[(i, k)] for k in range(j)]
                grid[(i, j)] = UpBlock(
                    self._w(self.features[i]),
                    up_sample_mode=self.up_sample_mode,
                    name=f"x{i}_{j}",
                    **common,
                )(grid[(i + 1, j - 1)], skips, train)

        refine = (
            DetailHead(
                self.num_classes,
                dtype=self.dtype,
                head_dtype=self.head_dtype,
                name="detail_head",
            )
            if self.detail_head
            else None
        )

        def head(h: jax.Array, name: str) -> jax.Array:
            logits = nn.Conv(
                head_channels(self.num_classes, self.stem, self.stem_factor),
                (1, 1),
                dtype=self.head_dtype,
                param_dtype=jnp.float32,
                name=name,
            )(h.astype(self.head_dtype))
            logits = restore_head(logits, self.stem, self.stem_factor)
            if refine is not None:
                logits = refine(logits, image)
            return logits

        if self.deep_supervision:
            logits = jnp.stack(
                [head(grid[(0, j)], f"head_{j}") for j in range(1, depth)]
            )
            # Ensemble-mean readout in fp32 regardless of head storage dtype.
            return (
                logits
                if train
                else jnp.mean(logits.astype(jnp.float32), axis=0)
            )
        return head(grid[(0, depth - 1)], "head")
