"""Shared Flax building blocks for the segmentation model zoo.

TPU-first conventions used throughout the zoo:
- NHWC activations (TPU conv layout; the reference is NCHW torch, кластер.py:737).
- bfloat16 compute / float32 params, selected per-module via ``dtype``.
- Normalization is pluggable: 'batch' (optionally cross-replica synced via
  ``axis_name`` — fixing the reference's silently drifting per-replica BN
  running stats, SURVEY §3.1), 'group', or 'none'.

Reference parity: DoubleConv = (Conv3×3 → BatchNorm2d → ReLU) ×2
(кластер.py:575-588); DownBlock = DoubleConv + MaxPool2d(2) returning
(down, skip) (кластер.py:591-600); UpBlock = ConvTranspose2d(k=2,s=2) or
bilinear upsample, concat skip, DoubleConv (кластер.py:603-617).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


class Norm(nn.Module):
    """Pluggable normalization layer.

    kind='batch' uses running-average BatchNorm; when ``axis_name`` is set and
    the module runs inside a mapped axis (shard_map/pmap), batch statistics
    are averaged across that axis — true sync-BN, unlike the reference which
    never re-syncs running stats after the init broadcast (кластер.py:560-565).
    """

    kind: str = "batch"
    axis_name: Optional[str] = None
    groups: int = 8
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        if self.kind == "batch":
            return nn.BatchNorm(
                use_running_average=not train,
                axis_name=self.axis_name if train else None,
                momentum=0.9,
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )(x)
        if self.kind == "group":
            groups = min(self.groups, x.shape[-1])
            while x.shape[-1] % groups:
                groups -= 1
            return nn.GroupNorm(
                num_groups=groups, dtype=self.dtype, param_dtype=jnp.float32
            )(x)
        if self.kind == "none":
            return x
        raise ValueError(f"unknown norm kind {self.kind!r}")


class ConvNormAct(nn.Module):
    """3×3 same-padding conv → norm → ReLU (one half of reference DoubleConv)."""

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    dilation: int = 1
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        x = nn.Conv(
            self.features,
            self.kernel_size,
            strides=self.strides,
            padding="SAME",
            kernel_dilation=(self.dilation, self.dilation),
            use_bias=self.norm == "none",
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)
        x = Norm(
            kind=self.norm,
            axis_name=self.norm_axis_name,
            groups=self.norm_groups,
            dtype=self.dtype,
        )(x, train)
        return nn.relu(x)


class DoubleConv(nn.Module):
    """(Conv3×3 → norm → ReLU) ×2 — reference DoubleConv (кластер.py:575-588)."""

    features: int
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        for _ in range(2):
            x = ConvNormAct(
                self.features,
                norm=self.norm,
                norm_axis_name=self.norm_axis_name,
                norm_groups=self.norm_groups,
                dtype=self.dtype,
            )(x, train)
        return x


def max_pool_2x2(x: jax.Array) -> jax.Array:
    """2×2/stride-2 max pool over NHWC (reference MaxPool2d(2), кластер.py:596)."""
    return nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))


class DownBlock(nn.Module):
    """DoubleConv then 2× downsample; returns (downsampled, skip)
    (reference DownBlock, кластер.py:591-600)."""

    features: int
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True):
        skip = DoubleConv(
            self.features,
            norm=self.norm,
            norm_axis_name=self.norm_axis_name,
            norm_groups=self.norm_groups,
            dtype=self.dtype,
        )(x, train)
        return max_pool_2x2(skip), skip


def space_to_depth(x: jax.Array, r: int) -> jax.Array:
    """[B, H, W, C] → [B, H/r, W/r, C·r²] — trades spatial for channel extent.

    TPU-first stem transform: the MXU wants large channel counts, but a
    segmentation net's first levels run few channels at high resolution,
    where the (8, 128) register tiling pads C=3/C=32 up to full lanes and
    wastes most of the bandwidth and systolic array (measured: the s2d stem
    is ~2.6× faster end-to-end for the flagship U-Net at 512²).
    """
    b, h, w, c = x.shape
    if h % r or w % r:
        raise ValueError(f"spatial dims {(h, w)} not divisible by r={r}")
    x = x.reshape(b, h // r, r, w // r, r, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // r, w // r, c * r * r)


def depth_to_space(x: jax.Array, r: int) -> jax.Array:
    """Inverse of :func:`space_to_depth` — the subpixel upsampling head."""
    b, h, w, c = x.shape
    if c % (r * r):
        raise ValueError(f"channels {c} not divisible by r²={r * r}")
    x = x.reshape(b, h, w, r, r, c // (r * r))
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h * r, w * r, c // (r * r))


def apply_stem(x: jax.Array, stem: str, factor: int) -> jax.Array:
    """Shared input-stem dispatch for the zoo: 'none' passes through, 's2d'
    space-to-depths by ``factor``.  One implementation so U-Net and U-Net++
    cannot diverge on validation or semantics."""
    if stem == "s2d":
        return space_to_depth(x, factor)
    if stem == "none":
        return x
    raise ValueError(f"unknown stem {stem!r}")


def head_channels(num_classes: int, stem: str, factor: int) -> int:
    """Logit-head channel count: ×factor² for subpixel heads under s2d."""
    return num_classes * factor * factor if stem == "s2d" else num_classes


def restore_head(logits: jax.Array, stem: str, factor: int) -> jax.Array:
    """Inverse of the stem on the logit grid (subpixel upsampling)."""
    return depth_to_space(logits, factor) if stem == "s2d" else logits


class DetailHead(nn.Module):
    """Full-resolution residual refinement for subpixel (s2d) heads.

    The subpixel head reconstructs full-res logits from 1/r-resolution
    features; structure finer than r px is measurably degraded — on the
    HardTiles stem A/B the 2-6 px disc class collapses to IoU 0.03 under
    s2d (docs/QUANTIZATION.md hard-task table) because the pyramid never
    sees the raw pixels at full resolution.  This head concatenates the RAW
    input image with the d2s logits and applies two cheap full-resolution
    convs as a residual correction:

        logits += Conv3x3(classes) . relu . Conv3x3(hidden) (logits ++ image)

    FLOPs are negligible next to the pyramid (C<=hidden at the stem's
    resolution); the real cost is HBM traffic for two low-channel full-res
    activations, measured ~2-5% of the flagship step.  No normalization:
    at C=16 a BatchNorm's scalar DMA chatter would cost more than the conv.
    """

    num_classes: int
    hidden: int = 16
    dtype: Dtype = jnp.bfloat16
    head_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, logits: jax.Array, image: jax.Array) -> jax.Array:
        z = jnp.concatenate(
            [logits.astype(self.dtype), image.astype(self.dtype)], axis=-1
        )
        z = nn.relu(
            nn.Conv(self.hidden, (3, 3), dtype=self.dtype, param_dtype=jnp.float32)(z)
        )
        delta = nn.Conv(
            self.num_classes, (3, 3), dtype=self.head_dtype, param_dtype=jnp.float32
        )(z.astype(self.head_dtype))
        return logits + delta


class StemGridDetailHead(nn.Module):
    """Residual refinement computed AT THE STEM GRID (detail_head_kind='s2d').

    The full-resolution DetailHead above buys its quality with the worst-
    shaped convs in the net: C=9→16 at 512² runs lane-padded at 9-37 TF/s
    and its weight gradients contract over [B, H·W] — measured ~43% of the
    round-3 flagship step (docs/PERF.md roofline).  This variant computes
    the SAME residual-correction idea without ever leaving the stem grid:

        z += Conv3x3(C·r²) . relu . Conv3x3(hidden) (z ++ s2d(image))

    where z is the pre-depth_to_space logit tensor [B, H/r, W/r, C·r²] and
    s2d(image) packs every raw pixel losslessly into 3·r² channels — the
    head sees exactly the information the full-res head sees.  What changes
    is the equivariance group: weights are shared across stem CELLS, not
    pixels, so each of the r² subpixel phases gets its own filters (more
    parameters per FLOP, cell-level instead of pixel-level translation
    equivariance).  A 3×3 conv here spans 3r×3r raw pixels of context vs
    the full-res head's 3×3.  Every conv lands in the MXU-efficient
    channel regime (C≥96 for the flagship's r=4).

    Quality is an empirical question per task — measured on the HardTiles
    sweep (docs/HARD_TASK.md round-4 table) rather than assumed.
    """

    num_classes: int
    stem_factor: int
    hidden: int = 64
    dtype: Dtype = jnp.bfloat16
    head_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array, image: jax.Array) -> jax.Array:
        r = self.stem_factor
        zin = jnp.concatenate(
            [z.astype(self.dtype), space_to_depth(image.astype(self.dtype), r)],
            axis=-1,
        )
        y = nn.relu(
            nn.Conv(self.hidden, (3, 3), dtype=self.dtype, param_dtype=jnp.float32)(zin)
        )
        delta = nn.Conv(
            self.num_classes * r * r,
            (3, 3),
            dtype=self.head_dtype,
            param_dtype=jnp.float32,
        )(y.astype(self.head_dtype))
        return z + delta


def group_labels(labels: jax.Array, r: int) -> jax.Array:
    """[..., H, W] int labels → [..., H/r, W/r, r²], phase-major — the label
    grouping that matches the channel order of pre-depth_to_space logits
    [..., H/r, W/r, r²·C] (reshape to [..., r², C] pairs phase p's class row
    with this function's phase-p label).  With it, the train path can run
    losses/metrics on the grouped view — identical math to full resolution,
    same multiset of (logit row, label) pairs — without the d2s transpose or
    any full-res tensor (ModelConfig.train_head_layout='grouped')."""
    *lead, h, w = labels.shape
    if h % r or w % r:
        raise ValueError(f"spatial dims {(h, w)} not divisible by r={r}")
    x = labels.reshape(*lead, h // r, r, w // r, r)
    x = jnp.moveaxis(x, -3, -2)  # [..., h/r, w/r, r, r]
    return x.reshape(*lead, h // r, w // r, r * r)


def upsample_2x(x: jax.Array, method: str = "bilinear") -> jax.Array:
    """2× spatial upsample of NHWC via jax.image.resize."""
    n, h, w, c = x.shape
    return jax.image.resize(x, (n, 2 * h, 2 * w, c), method=method).astype(x.dtype)


class UpBlock(nn.Module):
    """2× upsample (transposed conv or bilinear), concat skip(s), DoubleConv
    (reference UpBlock, кластер.py:603-617).

    ``phase`` exists for pipeline staging (parallel/pipeline.py): the
    decoder's DoubleConvs are the heaviest modules in the tree, so block
    granularity alone cannot balance a 2-stage cut — ``'up'`` runs just
    upsample+concat (returns the concatenated tensor), ``'conv'`` runs
    just the DoubleConv on it.  ``'all'`` (default, and the only path the
    unstaged builders take) is both in one call — explicit submodule
    names pin the param tree identical across phases, so checkpoints and
    stage rule tables agree regardless of where the cut lands."""

    features: int
    up_sample_mode: str = "conv_transpose"
    norm: str = "batch"
    norm_axis_name: Optional[str] = None
    norm_groups: int = 8
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, x: jax.Array, skips, train: bool = True, phase: str = "all"
    ) -> jax.Array:
        if phase not in ("all", "up", "conv"):
            raise ValueError(f"unknown UpBlock phase {phase!r}")
        if phase in ("all", "up"):
            if self.up_sample_mode == "conv_transpose":
                x = nn.ConvTranspose(
                    self.features,
                    kernel_size=(2, 2),
                    strides=(2, 2),
                    dtype=self.dtype,
                    param_dtype=jnp.float32,
                    name="ConvTranspose_0",
                )(x)
            elif self.up_sample_mode == "bilinear":
                x = upsample_2x(x, "bilinear")
            else:
                raise ValueError(
                    f"unknown up_sample_mode {self.up_sample_mode!r}"
                )
            if not isinstance(skips, (list, tuple)):
                skips = (skips,)
            x = jnp.concatenate([*skips, x], axis=-1)
            if phase == "up":
                return x
        return DoubleConv(
            self.features,
            norm=self.norm,
            norm_axis_name=self.norm_axis_name,
            norm_groups=self.norm_groups,
            dtype=self.dtype,
            name="DoubleConv_0",
        )(x, train)
