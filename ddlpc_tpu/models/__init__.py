"""Model zoo registry.

Build any registered model from a ``ModelConfig``.  The reference has exactly
one model, U-Net (кластер.py:620-656); BASELINE.json's configs additionally
require U-Net++ (deep supervision) and DeepLabV3+ (ASPP/atrous).
"""

from __future__ import annotations

from typing import Optional

from flax import linen as nn

from ddlpc_tpu.config import ModelConfig
from ddlpc_tpu.models.deeplabv3p import DeepLabV3Plus
from ddlpc_tpu.models.unet import UNet
from ddlpc_tpu.models.unetpp import UNetPP

_REGISTRY = {}
# Models that implement ModelConfig.detail_head.  Checked centrally in
# build_model so a newly registered model is safe by default: a config
# artifact must never claim a refinement head the built network lacks
# (same principle as the GSPMD quantize_local rejection,
# parallel/train_step.py).
_DETAIL_HEAD_MODELS = {"unet", "unetpp"}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


@register("unet")
def _build_unet(cfg: ModelConfig, norm_axis_name: Optional[str]) -> nn.Module:
    import jax.numpy as jnp

    return UNet(
        num_classes=cfg.num_classes,
        features=tuple(cfg.features),
        bottleneck_features=cfg.bottleneck_features,
        width_divisor=cfg.width_divisor,
        up_sample_mode=cfg.up_sample_mode,
        norm=cfg.norm,
        norm_axis_name=norm_axis_name,
        norm_groups=cfg.group_norm_groups,
        stem=cfg.stem,
        stem_factor=cfg.stem_factor,
        detail_head=cfg.detail_head,
        detail_head_kind=cfg.detail_head_kind,
        detail_head_hidden=cfg.detail_head_hidden,
        train_head_layout=cfg.train_head_layout,
        dtype=jnp.dtype(cfg.compute_dtype),
        head_dtype=jnp.dtype(cfg.head_dtype),
    )


@register("unetpp")
def _build_unetpp(cfg: ModelConfig, norm_axis_name: Optional[str]) -> nn.Module:
    import jax.numpy as jnp

    return UNetPP(
        num_classes=cfg.num_classes,
        features=tuple(cfg.features),
        width_divisor=cfg.width_divisor,
        up_sample_mode=cfg.up_sample_mode,
        norm=cfg.norm,
        norm_axis_name=norm_axis_name,
        norm_groups=cfg.group_norm_groups,
        deep_supervision=cfg.deep_supervision,
        stem=cfg.stem,
        stem_factor=cfg.stem_factor,
        detail_head=cfg.detail_head,
        detail_head_kind=cfg.detail_head_kind,
        detail_head_hidden=cfg.detail_head_hidden,
        detail_head_scope=cfg.detail_head_scope,
        train_head_layout=cfg.train_head_layout,
        dtype=jnp.dtype(cfg.compute_dtype),
        head_dtype=jnp.dtype(cfg.head_dtype),
    )


@register("deeplabv3p")
def _build_deeplab(cfg: ModelConfig, norm_axis_name: Optional[str]) -> nn.Module:
    import jax.numpy as jnp

    return DeepLabV3Plus(
        num_classes=cfg.num_classes,
        features=tuple(cfg.features),
        width_divisor=cfg.width_divisor,
        output_stride=cfg.output_stride,
        aspp_rates=tuple(cfg.aspp_rates),
        norm=cfg.norm,
        norm_axis_name=norm_axis_name,
        norm_groups=cfg.group_norm_groups,
        dtype=jnp.dtype(cfg.compute_dtype),
        head_dtype=jnp.dtype(cfg.head_dtype),
    )


def build_model(cfg: ModelConfig, norm_axis_name: Optional[str] = None) -> nn.Module:
    """norm_axis_name: mesh axis to sync BatchNorm stats over (None = local)."""
    try:
        builder = _REGISTRY[cfg.name]
    except KeyError:
        raise ValueError(
            f"unknown model {cfg.name!r}; registered: {sorted(_REGISTRY)}"
        ) from None
    if cfg.detail_head and cfg.name not in _DETAIL_HEAD_MODELS:
        raise ValueError(
            f"model {cfg.name!r} does not implement detail_head "
            f"(supported: {sorted(_DETAIL_HEAD_MODELS)}) — set "
            f"model.detail_head=False"
        )
    # The layout/kind combinations are validated HERE, not silently ignored
    # in the model: a config artifact claiming a layout the built network
    # would not execute is a lie in the artifact (same principle as the
    # GSPMD quantize_local rejection, parallel/train_step.py).
    if cfg.detail_head_kind not in ("fullres", "s2d"):
        raise ValueError(
            f"unknown detail_head_kind {cfg.detail_head_kind!r} "
            f"(fullres | s2d)"
        )
    if cfg.train_head_layout not in ("fullres", "grouped"):
        raise ValueError(
            f"unknown train_head_layout {cfg.train_head_layout!r} "
            f"(fullres | grouped)"
        )
    if cfg.detail_head_scope not in ("per_head", "ensemble"):
        raise ValueError(
            f"unknown detail_head_scope {cfg.detail_head_scope!r} "
            f"(per_head | ensemble)"
        )
    if cfg.detail_head and cfg.detail_head_kind == "s2d" and cfg.stem != "s2d":
        raise ValueError(
            "detail_head_kind='s2d' refines the pre-d2s logit grid and "
            "requires stem='s2d'; with stem='none' use "
            "detail_head_kind='fullres'"
        )
    if cfg.train_head_layout == "grouped":
        if cfg.stem != "s2d":
            raise ValueError(
                "train_head_layout='grouped' skips the subpixel d2s in the "
                "train path — it requires stem='s2d'"
            )
        if cfg.detail_head and cfg.detail_head_kind == "fullres":
            raise ValueError(
                "train_head_layout='grouped' cannot feed a full-resolution "
                "DetailHead (it needs full-res logits): use "
                "detail_head_kind='s2d' or train_head_layout='fullres'"
            )
        if cfg.name not in _DETAIL_HEAD_MODELS:
            raise ValueError(
                f"model {cfg.name!r} does not implement "
                f"train_head_layout='grouped' (supported: "
                f"{sorted(_DETAIL_HEAD_MODELS)})"
            )
    return builder(cfg, norm_axis_name)


def build_model_from_experiment(ecfg) -> nn.Module:
    """Build honoring ParallelConfig.sync_batch_norm: per-batch cross-replica
    BN stat averaging over the data axis (the reference never re-syncs BN,
    SURVEY §3.1).

    With a non-trivial space axis the GSPMD step is used
    (parallel/train_step.py:make_train_step_gspmd), where BN statistics are
    computed over the logical global batch — exact sync-BN without an axis
    name — so ``norm_axis_name`` must stay None there.
    """
    axis = (
        ecfg.parallel.data_axis_name
        if ecfg.parallel.sync_batch_norm and ecfg.parallel.space_axis_size <= 1
        else None
    )
    return build_model(ecfg.model, norm_axis_name=axis)
