"""Fleet telemetry aggregation: N registries → one ``ddlpc_fleet_*`` scrape.

PR 9's fleet left metrics sharded: the router's registry answers on the
fleet ``/metrics``, but every replica's ``ddlpc_serve_*`` series live
behind that replica's own ephemeral port — no single scrape answers "what
is the FLEET doing".  :class:`TelemetryAggregator` closes that gap
(ISSUE 14 tentpole): on a cadence it pulls every source's Prometheus text
exposition (replica ``/metrics`` over HTTP, the router's registry
in-process), and re-publishes each ``ddlpc_<x>`` family as
``ddlpc_fleet_<x>`` with

- **per-replica series preserved** — every scraped series gains a
  ``replica`` label naming its source;
- **one rollup series** per label-set at ``replica="fleet"`` — counters
  and histograms (bucket-by-bucket, sums of cumulative counts stay
  cumulative) SUM across sources; gauges take the MAX (a queue-depth or
  busy-fraction rollup answers "how bad is the worst replica", which is
  the question a gauge's operator is asking);
- **staleness discipline** — a source whose last successful scrape is
  older than ``stale_after_s`` is flagged
  (``ddlpc_fleet_source_stale{replica}=1``) and its GAUGES leave the
  rollup (a dead replica's frozen queue depth must not pose as the
  fleet's worst); its counters/histograms keep contributing their last
  cumulative values — a counter rollup is "work the fleet has done" and
  must stay MONOTONIC, or a downstream ``rate()`` reads the dip as a
  counter reset.  For the same reason :meth:`remove_source` retires a
  departing source's summed values into offsets: a supervised replica
  restart (remove at death, fresh add at readiness) resets the
  per-replica series — which Prometheus handles per series — but never
  walks the fleet totals backwards.

Deliberately jax-free and dependency-free (stdlib only), like the router:
the aggregator runs in the fleet front-end process, which never pays an
XLA import.  The text-format parser handles exactly the v0.0.4 subset
``obs/registry.py`` emits — which is the only dialect in this system.
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

FLEET_PREFIX = "ddlpc_fleet_"
_SOURCE_PREFIX = "ddlpc_"
ROLLUP_LABEL = "fleet"  # the aggregate series' replica label value

_SERIES_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Family:
    """One metric family from an exposition: declared kind + help and the
    raw samples (sample name, label tuple, value).  Histogram samples keep
    their ``_bucket``/``_sum``/``_count`` suffixes and ``le`` labels."""

    def __init__(self, name: str, kind: str = "untyped", help: str = ""):
        self.name = name
        self.kind = kind
        self.help = help
        self.samples: List[Tuple[str, Tuple[Tuple[str, str], ...], float]] = []


def parse_exposition(text: str) -> Dict[str, Family]:
    """Families from a Prometheus text exposition (v0.0.4 subset —
    ``obs/registry.py``'s own output shape).  Unparseable lines are
    skipped: a torn scrape degrades, never raises."""
    families: Dict[str, Family] = {}
    # sample name -> family base name (histogram suffixes map back)
    owner: Dict[str, str] = {}

    def family(name: str) -> Family:
        fam = families.get(name)
        if fam is None:
            fam = families[name] = Family(name)
        return fam

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                fam = family(parts[2])
                if parts[1] == "TYPE":
                    fam.kind = parts[3].strip() if len(parts) > 3 else "untyped"
                    owner[parts[2]] = parts[2]
                    if fam.kind == "histogram":
                        for sfx in ("_bucket", "_sum", "_count"):
                            owner[parts[2] + sfx] = parts[2]
                else:
                    fam.help = parts[3] if len(parts) > 3 else ""
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        sample_name, labels_raw, value_raw = m.groups()
        try:
            if value_raw == "+Inf":
                value = float("inf")
            elif value_raw == "-Inf":
                value = float("-inf")
            else:
                value = float(value_raw)
        except ValueError:
            continue
        labels: List[Tuple[str, str]] = []
        if labels_raw:
            for lm in _LABEL_RE.finditer(labels_raw):
                labels.append((lm.group(1), _unescape(lm.group(2))))
        base = owner.get(sample_name, sample_name)
        family(base).samples.append((sample_name, tuple(labels), value))
    return families


class _Source:
    def __init__(self, name: str, fetch: Callable[[], str]):
        self.name = name
        self.fetch = fetch
        self.families: Dict[str, Family] = {}
        self.last_ok: Optional[float] = None  # clock of last good scrape
        self.failures = 0


def _is_summed(kind: str, sample_name: str) -> bool:
    """True for sample kinds whose rollup is a SUM of cumulative values
    (counters, histogram buckets/sums/counts, untyped); gauges roll up as
    the max of FRESH sources."""
    return kind != "gauge" or sample_name.endswith(
        ("_sum", "_count", "_bucket")
    )


def _fleet_samples(fam: Family):
    """(out_name, kind, help, out_sample, labels, value) for one scraped
    family's re-publication as ``ddlpc_fleet_*``.  A source label already
    named ``replica`` (the router's own per-replica families) renames to
    ``src_replica`` — the aggregator OWNS the ``replica`` label and the
    text format forbids two labels with one name."""
    if not fam.name.startswith(_SOURCE_PREFIX):
        return
    if fam.name.startswith(FLEET_PREFIX):
        return  # never re-aggregate an aggregate
    out_name = FLEET_PREFIX + fam.name[len(_SOURCE_PREFIX):]
    suffix_shift = len(fam.name)
    for sample_name, labels, value in fam.samples:
        out_sample = out_name + sample_name[suffix_shift:]
        labels = tuple(
            ("src_replica" if ln == "replica" else ln, lv)
            for ln, lv in labels
        )
        yield out_name, fam.kind, fam.help, out_sample, labels, value


class TelemetryAggregator:
    """Scrape-and-rollup engine for the fleet ``/metrics``.

    ``add_source(name, fetch)`` registers one telemetry source — ``fetch``
    returns a Prometheus text exposition (an HTTP replica's
    ``metrics_text``, or ``registry.exposition`` for the in-process
    router).  ``scrape_once()`` pulls every source;
    ``exposition()``/``snapshot()`` render the current rollups.  The
    optional background loop (:meth:`start`) runs the scrape on a cadence
    so a fleet scrape is always at most ``every_s`` old.

    Thread-safe: sources come and go as replicas restart (the fleet
    supervisor registers them at readiness, exactly like the router).
    """

    def __init__(
        self,
        stale_after_s: float = 15.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.stale_after_s = float(stale_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._sources: Dict[str, _Source] = {}
        # Cumulative offsets from REMOVED sources, per rollup key — what
        # keeps counter/histogram rollups monotonic across the supervised
        # remove-at-death / add-at-readiness replica lifecycle.
        self._retired: Dict[Tuple[str, str, Tuple], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sources -------------------------------------------------------------

    def add_source(self, name: str, fetch: Callable[[], str]) -> None:
        with self._lock:
            self._sources[name] = _Source(name, fetch)

    def remove_source(self, name: str) -> None:
        """Drop a source, retiring its last SUMMED values (counters,
        histogram buckets/sums/counts, untyped) into rollup offsets —
        the fleet's cumulative totals never decrease because one replica
        process ended."""
        with self._lock:
            src = self._sources.pop(name, None)
            if src is None:
                return
            for fam in src.families.values():
                for out_name, kind, _, out_sample, labels, value in (
                    _fleet_samples(fam)
                ):
                    if _is_summed(kind, out_sample):
                        key = (out_name, out_sample, labels)
                        self._retired[key] = (
                            self._retired.get(key, 0.0) + value
                        )

    def source_names(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    # -- scraping ------------------------------------------------------------

    def scrape_once(self) -> Dict[str, bool]:
        """One pass over every source; per-source success map.  A failed
        fetch keeps the source's LAST families (the stale flag and the
        rollup exclusion say so — see class docstring)."""
        with self._lock:
            sources = list(self._sources.values())
        out: Dict[str, bool] = {}
        for src in sources:
            try:
                families = parse_exposition(src.fetch())
            except Exception:
                with self._lock:
                    src.failures += 1
                out[src.name] = False
                continue
            with self._lock:
                src.families = families
                src.last_ok = self._clock()
            out[src.name] = True
        return out

    def start(self, every_s: float) -> "TelemetryAggregator":
        if self._thread is None and every_s > 0:
            def loop() -> None:
                while not self._stop.wait(every_s):
                    try:
                        self.scrape_once()
                    except Exception:
                        pass  # aggregation must never kill the front end

            self._thread = threading.Thread(
                target=loop, name="fleet-aggregate", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- rollup --------------------------------------------------------------

    def _collect(self, now: float):
        """(merged families, per-source freshness, retired offsets) under
        one lock pass."""
        with self._lock:
            sources = [
                (s.name, s.families, s.last_ok) for s in self._sources.values()
            ]
            retired = dict(self._retired)
        fresh: Dict[str, bool] = {}
        for name, _, last_ok in sources:
            fresh[name] = (
                last_ok is not None and now - last_ok <= self.stale_after_s
            )
        merged: Dict[str, dict] = {}
        for sname, families, _ in sources:
            for fam in families.values():
                for out_name, kind, help_, out_sample, labels, value in (
                    _fleet_samples(fam)
                ):
                    slot = merged.setdefault(
                        out_name,
                        {"kind": kind, "help": help_, "samples": []},
                    )
                    if slot["kind"] == "untyped" and kind != "untyped":
                        slot["kind"] = kind
                    slot["samples"].append(
                        (out_sample, labels, value, sname)
                    )
        return merged, fresh, retired

    def _rollups(
        self, slot: dict, fresh: Dict[str, bool],
        retired: Dict[Tuple[str, str, Tuple], float], out_name: str,
    ) -> Dict[Tuple[str, Tuple], float]:
        """One aggregate value per (sample, label-set).  Summed kinds
        (counters, histogram buckets/sums/counts, untyped) sum EVERY
        present source — stale ones included, their frozen values are
        still cumulative truth — plus the retired offsets, so the series
        is monotonic across replica restarts.  Gauges take the max of
        FRESH sources only (a dead replica's frozen queue depth must not
        pose as the fleet's worst) and vanish with their last fresh
        source."""
        kind = slot["kind"]
        summed: Dict[Tuple[str, Tuple], float] = {}
        gauge_vals: Dict[Tuple[str, Tuple], List[float]] = {}
        for sample_name, labels, value, sname in slot["samples"]:
            key = (sample_name, labels)
            if _is_summed(kind, sample_name):
                summed[key] = summed.get(key, 0.0) + value
            elif fresh.get(sname):
                gauge_vals.setdefault(key, []).append(value)
        for (rname, rsample, rlabels), offset in retired.items():
            if rname == out_name:
                key = (rsample, rlabels)
                summed[key] = summed.get(key, 0.0) + offset
        out = dict(summed)
        for key, values in gauge_vals.items():
            out[key] = max(values)
        return out

    def render(self, now: Optional[float] = None) -> List[str]:
        """The ``ddlpc_fleet_*`` exposition lines: per-replica series plus
        one rollup series per label-set, plus the aggregator's own
        freshness series."""
        now = self._clock() if now is None else now
        merged, fresh, retired = self._collect(now)
        lines: List[str] = []
        for out_name in sorted(merged):
            slot = merged[out_name]
            kind = slot["kind"]
            if slot["help"]:
                lines.append(f"# HELP {out_name} {slot['help']} (fleet rollup)")
            # Everything re-exposes as untyped except gauges: the
            # per-replica + rollup mixture under one name is a federation
            # shape, and a counter rollup spanning restarting sources is
            # monotonic by construction here but not a native counter
            # family either.
            expo_kind = "gauge" if kind == "gauge" else "untyped"
            lines.append(f"# TYPE {out_name} {expo_kind}")
            for sample_name, labels, value, sname in sorted(
                slot["samples"], key=lambda s: (s[0], s[1], s[3])
            ):
                pairs = [
                    f'{ln}="{_escape(lv)}"' for ln, lv in labels
                ] + [f'replica="{_escape(sname)}"']
                lines.append(
                    f"{sample_name}{{{','.join(pairs)}}} {_fmt(value)}"
                )
            rollup = self._rollups(slot, fresh, retired, out_name)
            for (sample_name, labels), value in sorted(rollup.items()):
                pairs = [
                    f'{ln}="{_escape(lv)}"' for ln, lv in labels
                ] + [f'replica="{ROLLUP_LABEL}"']
                lines.append(
                    f"{sample_name}{{{','.join(pairs)}}} {_fmt(value)}"
                )
        # Aggregator self-telemetry: scrape freshness per source.
        with self._lock:
            ages = {
                s.name: (
                    None if s.last_ok is None else now - s.last_ok
                )
                for s in self._sources.values()
            }
        if ages:
            lines.append(
                "# HELP ddlpc_fleet_source_stale 1 when a source's last "
                "successful scrape is older than stale_after_s (its series "
                "are excluded from rollups)."
            )
            lines.append("# TYPE ddlpc_fleet_source_stale gauge")
            for name in sorted(ages):
                stale = int(not fresh.get(name, False))
                lines.append(
                    f'ddlpc_fleet_source_stale{{replica="{_escape(name)}"}} '
                    f"{stale}"
                )
            lines.append(
                "# HELP ddlpc_fleet_scrape_age_seconds Seconds since each "
                "source's last successful scrape."
            )
            lines.append("# TYPE ddlpc_fleet_scrape_age_seconds gauge")
            for name in sorted(ages):
                age = ages[name]
                if age is not None:
                    lines.append(
                        "ddlpc_fleet_scrape_age_seconds"
                        f'{{replica="{_escape(name)}"}} {_fmt(age)}'
                    )
        return lines

    def exposition(self, now: Optional[float] = None) -> str:
        lines = self.render(now)
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self, now: Optional[float] = None) -> Dict[str, object]:
        """Flat JSON view of the ROLLUP series only (the JSON /metrics
        fallback stays scannable; per-replica detail is the text
        exposition's job)."""
        now = self._clock() if now is None else now
        merged, fresh, retired = self._collect(now)
        out: Dict[str, object] = {}
        for out_name in sorted(merged):
            slot = merged[out_name]
            rollup = self._rollups(slot, fresh, retired, out_name)
            for (sample_name, labels), value in sorted(rollup.items()):
                sfx = (
                    "{" + ",".join(f'{ln}="{lv}"' for ln, lv in labels) + "}"
                    if labels
                    else ""
                )
                out[f"{sample_name}{sfx}"] = value
        out["ddlpc_fleet_sources_fresh"] = sum(
            1 for v in fresh.values() if v
        )
        out["ddlpc_fleet_sources_total"] = len(fresh)
        return out
