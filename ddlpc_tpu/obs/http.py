"""Telemetry over HTTP: content negotiation + the training-side endpoint.

:func:`render_metrics` is the one owner of the ``/metrics`` content
negotiation used by BOTH http layers (serve/server.py and the
:class:`TelemetryServer` below): JSON stays the default (existing tooling
and the serve bench parse it), Prometheus text exposition is selected by
an ``Accept`` header naming ``text/plain`` or ``openmetrics`` — which is
what Prometheus' own scraper sends.

:class:`TelemetryServer` gives *training* runs the scrape surface serving
already had: a stdlib threading HTTP server on a daemon thread, serving

- ``GET /metrics``   — negotiated (Prometheus text ⟷ JSON snapshot);
- ``GET /healthz``   — liveness + recent health alerts;
- ``GET /debug/trace?steps=N`` — arms the on-demand profiler; the capture
  runs inside the training loop (the next N steps) and the report lands in
  the run dir, so the response acknowledges the arm rather than blocking
  an HTTP thread for N step times.

It deliberately runs even while the training loop is busy (its own
threads), costs nothing per step, and is off unless
``TrainConfig.telemetry_port >= 0``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ddlpc_tpu.obs.registry import MetricsRegistry

PROMETHEUS_CTYPE = "text/plain; version=0.0.4; charset=utf-8"


def wants_prometheus(accept: Optional[str]) -> bool:
    """Whether an Accept header asks for the text exposition format."""
    if not accept:
        return False
    accept = accept.lower()
    return "text/plain" in accept or "openmetrics" in accept


def render_metrics(
    registry: MetricsRegistry,
    accept: Optional[str],
    json_fallback: Optional[Callable[[], dict]] = None,
) -> Tuple[str, bytes]:
    """(content type, body) for a ``/metrics`` request.

    JSON default keeps every existing consumer working; ``json_fallback``
    supplies the legacy JSON body (the serve snapshot) — without one the
    registry's own flat snapshot is served.
    """
    if wants_prometheus(accept):
        return PROMETHEUS_CTYPE, registry.exposition().encode()
    obj = json_fallback() if json_fallback is not None else registry.snapshot()
    return "application/json", json.dumps(obj).encode()


class TelemetryServer:
    """Scrape endpoint for a training process; start()/close() lifecycle."""

    def __init__(
        self,
        registry: MetricsRegistry,
        host: str = "127.0.0.1",
        port: int = 0,
        health_fn: Optional[Callable[[], dict]] = None,
        arm_profile_fn: Optional[Callable[[int], dict]] = None,
        json_fn: Optional[Callable[[], dict]] = None,
    ):
        self.registry = registry
        self.host = host
        self._port = port
        self.health_fn = health_fn
        self.arm_profile_fn = arm_profile_fn
        self.json_fn = json_fn
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    def start(self) -> "TelemetryServer":
        if self._server is not None:
            return self
        telemetry = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "ddlpc-telemetry/1"
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # scrape traffic is not news
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, obj: dict) -> None:
                self._send(code, "application/json", json.dumps(obj).encode())

            def do_GET(self) -> None:
                parsed = urlparse(self.path)
                if parsed.path == "/metrics":
                    ctype, body = render_metrics(
                        telemetry.registry,
                        self.headers.get("Accept"),
                        json_fallback=telemetry.json_fn,
                    )
                    self._send(200, ctype, body)
                elif parsed.path == "/healthz":
                    obj = (
                        telemetry.health_fn()
                        if telemetry.health_fn is not None
                        else {"status": "ok"}
                    )
                    self._send_json(200, obj)
                elif parsed.path == "/debug/trace":
                    if telemetry.arm_profile_fn is None:
                        self._send_json(
                            501, {"error": "no profiler wired to this endpoint"}
                        )
                        return
                    q = parse_qs(parsed.query)
                    try:
                        steps = int(q["steps"][0]) if "steps" in q else 0
                    except ValueError:
                        self._send_json(400, {"error": "steps must be an int"})
                        return
                    self._send_json(200, telemetry.arm_profile_fn(steps))
                else:
                    self._send_json(404, {"error": f"no route {parsed.path}"})

        self._server = ThreadingHTTPServer((self.host, self._port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="telemetry-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def close(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None
