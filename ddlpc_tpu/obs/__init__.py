"""Unified telemetry for the whole stack (docs/OBSERVABILITY.md).

One subsystem, four capabilities, shared by training and serving:

- :mod:`tracing` — span-based tracer (``Span``/``Tracer``) with JSONL and
  Chrome-trace-event exporters, near-zero overhead when disabled;
- :mod:`registry` — Prometheus-style Counter/Gauge/Histogram registry with
  text exposition, published into by both ``MetricsLogger`` (train) and
  ``ServeMetrics`` (serve);
- :mod:`profiling` + :mod:`xplane` — on-demand ``jax.profiler`` capture
  (SIGUSR2 in the Trainer, ``/debug/trace`` in serve) aggregated through
  the xplane self-time logic into a committed-format top-ops report;
- :mod:`health` — EWMA step-time regression, loss NaN/spike and serve
  queue-saturation detectors emitting structured alert records into the
  metrics stream and the ``StallWatchdog``'s diagnosis;
- :mod:`flops` / :mod:`comm` / :mod:`hbm` — performance accounting
  (docs/PERF.md "Accounting"): the per-step conv FLOP model behind the
  live ``ddlpc_mfu``/``ddlpc_goodput`` gauges, exact per-collective wire
  byte counters + the fenced comm-time probe, and per-device HBM gauges
  from shape × committed sharding;
- :mod:`merge` / :mod:`aggregate` — the fleet layer
  (docs/OBSERVABILITY.md "Distributed tracing & fleet aggregation"):
  per-process span streams stitched into one Perfetto timeline on the
  W3C-style ``traceparent`` context, and every replica's registry rolled
  up into ``ddlpc_fleet_*`` on the fleet ``/metrics``; SLO error budgets
  + burn-rate alerts live in :mod:`health` (``SLOTracker``).

Everything except :mod:`profiling`/:mod:`xplane` is pure stdlib — no jax
import at module scope — so the tracer and registry are importable (and
testable) anywhere, including the serve path's worker threads.

``SCHEMA_VERSION`` stamps every JSONL metrics/span/alert record; the
``scripts/check_metrics_schema.py`` lint (invoked from tier-1) keeps the
"tooling tails any stream unchanged" contract honest.
"""

from __future__ import annotations

from ddlpc_tpu.obs.schema import (  # noqa: E402
    KNOWN_KINDS,
    SCHEMA_VERSION,
    check_record,
    is_stale,
)

from ddlpc_tpu.obs.health import (  # noqa: E402
    Alert,
    EwmaRegressionDetector,
    HealthMonitor,
    LossDetector,
    QueueSaturationDetector,
)
from ddlpc_tpu.obs.registry import (  # noqa: E402
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from ddlpc_tpu.obs.tracing import NULL_SPAN, Span, Tracer  # noqa: E402

__all__ = [
    "KNOWN_KINDS",
    "SCHEMA_VERSION",
    "Alert",
    "Counter",
    "EwmaRegressionDetector",
    "Gauge",
    "HealthMonitor",
    "Histogram",
    "LossDetector",
    "MetricsRegistry",
    "NULL_SPAN",
    "QueueSaturationDetector",
    "Span",
    "Tracer",
    "check_record",
    "is_stale",
]
