"""Live per-device HBM accounting from shape × committed sharding.

The accounting ``scripts/hbm_report.py`` used to carry privately, hoisted
into the package (the ``obs/xplane.py`` precedent: one implementation for
the CLI and the live hooks): every leaf's per-device bytes come exactly
from ``sharding.shard_shape(global_shape) × itemsize`` — decided at
partitioning time, identically on every backend — so the numbers are
backend-independent and free to compute.

:func:`publish_hbm_gauges` turns a placed ``TrainState`` into
``ddlpc_hbm_bytes{kind=params|grads|opt_state|batch_stats}`` per-device
gauges on the training ``/metrics`` endpoint.  ``grads`` is the
accumulated fp32 gradient tree, which both step variants materialize at
full per-replica size between the backward pass and the sync (the ZeRO-1
path scatters AFTER accumulation — docs/SHARDING.md), so it is counted at
``Σ param_elements × 4`` regardless of the update layout.

jax is only needed for the tree walk; imported lazily like the rest of
``obs/``.
"""

from __future__ import annotations

from typing import Dict


def leaf_bytes_per_device(tree) -> int:
    """Per-device resident bytes of a pytree of placed jax Arrays (or
    ShapeDtypeStructs with shardings): Σ prod(shard_shape) × itemsize."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        shard_shape = leaf.sharding.shard_shape(leaf.shape)
        n = 1
        for d in shard_shape:
            n *= d
        total += n * leaf.dtype.itemsize
    return total


def grads_bytes_per_device(params) -> int:
    """Bytes of the accumulated fp32 gradient tree one device holds
    between backward and sync: full parameter element count × 4 (both the
    replicated and the ZeRO-1 paths accumulate full per-replica grads)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(params):
        total += int(np.prod(leaf.shape)) * 4
    return total


def state_hbm_bytes(state) -> Dict[str, int]:
    """Per-device byte breakdown of a placed TrainState, by kind."""
    return {
        "params": leaf_bytes_per_device(state.params),
        "grads": grads_bytes_per_device(state.params),
        "opt_state": leaf_bytes_per_device(state.opt_state),
        "batch_stats": leaf_bytes_per_device(state.batch_stats),
    }


def publish_hbm_gauges(registry, state) -> Dict[str, int]:
    """Set ``ddlpc_hbm_bytes{kind}`` gauges from a placed TrainState;
    returns the breakdown.  Static per run layout — the trainer publishes
    once after state placement."""
    gauge = registry.gauge(
        "ddlpc_hbm_bytes",
        "Per-device resident state bytes from shape x committed sharding "
        "(grads = accumulated fp32 gradient tree, full per replica).",
        labelnames=("kind",),
    )
    breakdown = state_hbm_bytes(state)
    for kind, nbytes in breakdown.items():
        gauge.set(float(nbytes), kind=kind)
    return breakdown
