"""Live per-device HBM accounting from shape × committed sharding.

The accounting ``scripts/hbm_report.py`` used to carry privately, hoisted
into the package (the ``obs/xplane.py`` precedent: one implementation for
the CLI and the live hooks): every leaf's per-device bytes come exactly
from ``sharding.shard_shape(global_shape) × itemsize`` — decided at
partitioning time, identically on every backend — so the numbers are
backend-independent and free to compute.

:func:`publish_hbm_gauges` turns a placed ``TrainState`` into
``ddlpc_hbm_bytes{kind=params|grads|grads_accum|opt_state|batch_stats}``
per-device gauges on the training ``/metrics`` endpoint.  Two gradient
kinds, because the ZeRO ladder splits the gradient's lifetime in two:

- ``grads`` — the OPTIMIZER-BOUNDARY gradient, what persists from the
  sync to the update.  Full fp32 under off/zero1 (the full mean), a
  1/N ``[1, K]`` chunk per device under zero2/zero3 (the reduce-scatter
  output IS the update input — docs/SHARDING.md).  This is the kind the
  1/N acceptance gauge watches.
- ``grads_accum`` — the full fp32 accumulator every layout materializes
  per replica between the backward pass and the sync (the scatter runs
  AFTER accumulation), counted at ``Σ param_elements × 4`` regardless of
  layout.  Honest ceiling: zero2/zero3 shrink the persistent gradient,
  not the transient backward peak.

:func:`publish_hbm_gauges` also publishes
``ddlpc_hbm_replicated_by_rule_bytes`` — the bytes the partition-rule
engine DECIDED to keep replicated (uneven GSPMD dims,
``partition.Decision.reason == 'replicated-by-rule'``) — so the PR 13
sharding contract budgets the fallback instead of special-casing it.

jax is only needed for the tree walk; imported lazily like the rest of
``obs/``.
"""

from __future__ import annotations

from typing import Dict

# Levels whose optimizer-boundary gradient persists as reduce-scattered
# 1/N chunks (parallel/shard_update.py ladder).
_SCATTERED_GRAD_LEVELS = ("zero2", "zero3")


def leaf_bytes_per_device(tree) -> int:
    """Per-device resident bytes of a pytree of placed jax Arrays (or
    ShapeDtypeStructs with shardings): Σ prod(shard_shape) × itemsize."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        shard_shape = leaf.sharding.shard_shape(leaf.shape)
        n = 1
        for d in shard_shape:
            n *= d
        total += n * leaf.dtype.itemsize
    return total


def grads_accum_bytes_per_device(params) -> int:
    """Bytes of the accumulated fp32 gradient tree one device holds
    between backward and sync: full parameter element count × 4 (every
    layout accumulates full per-replica grads; the scatter runs after)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(params):
        total += int(np.prod(leaf.shape)) * 4
    return total


def grads_bytes_per_device(
    params, level: str = "off", n_shards: int = 1
) -> int:
    """Bytes of the OPTIMIZER-BOUNDARY gradient one device holds — the
    sync output the update consumes.  Full fp32 for off/zero1 (the full
    mean); the per-leaf ``[1, ceil(n/N)]`` chunk (zero padding included,
    exactly what ``chunk_rows`` allocates) for zero2/zero3."""
    import jax
    import numpy as np

    if level in _SCATTERED_GRAD_LEVELS and n_shards > 1:
        from ddlpc_tpu.parallel.shard_update import chunk_rows

        total = 0
        for leaf in jax.tree.leaves(params):
            total += chunk_rows(int(np.prod(leaf.shape)), n_shards) * 4
        return total
    return grads_accum_bytes_per_device(params)


def state_hbm_bytes(
    state, level: str = "off", n_shards: int = 1
) -> Dict[str, int]:
    """Per-device byte breakdown of a placed TrainState, by kind.
    ``level`` is the resolved shard_update level (off|zero1|zero2|zero3);
    params/opt_state read their placement straight off the committed
    shardings, only the gradient kinds need the level (grads are step
    temporaries with no placed array to inspect)."""
    return {
        "params": leaf_bytes_per_device(state.params),
        "grads": grads_bytes_per_device(state.params, level, n_shards),
        "grads_accum": grads_accum_bytes_per_device(state.params),
        "opt_state": leaf_bytes_per_device(state.opt_state),
        "batch_stats": leaf_bytes_per_device(state.batch_stats),
    }


def pipeline_stage_hbm_bytes(stage_states, level: str = "off", n_shards: int = 1):
    """Per-stage, per-device byte breakdowns for a staged run
    (parallel/pipeline.py): each stage's placed TrainState priced by the
    same shape × committed-sharding math, with ``level``/``n_shards`` the
    ZeRO rung WITHIN the stage group.  The pipe-axis HBM claim reads off
    this: under ``pipe=S`` every kind that scales with parameters
    (params + grads + grads_accum + opt_state) drops to the stage's
    share — max-stage ≈ 1/S of the unstaged number when the cut is
    balanced (docs/SHARDING.md "Pipeline stages", bench.py
    --pipeline-ab)."""
    return [state_hbm_bytes(st, level, n_shards) for st in stage_states]


def pipeline_carry_stash_bytes(
    carry_avals, n_microbatches: int, n_data: int
) -> int:
    """Per-device bytes of the GPipe input-carry stash: a stage keeps the
    inter-stage activation carry of every in-flight microbatch until its
    backward recomputes from it (stage-granular remat — interior
    activations are NOT stashed), so the stash is ``M × carry_bytes``
    with the batch dimension sharded over the stage's data axis.  The
    memory the schedule — not the parameters — costs; grows linearly in
    M while the bubble (S-1)/(M+S-1) shrinks: the A/B's explicit
    trade-off."""
    import jax
    import numpy as np

    per_mb = 0
    for leaf in jax.tree.leaves(carry_avals):
        per_mb += int(np.prod(leaf.shape)) * jax.numpy.dtype(leaf.dtype).itemsize
    return (per_mb // max(1, n_data)) * int(n_microbatches)


def publish_hbm_gauges(
    registry,
    state,
    level: str = "off",
    n_shards: int = 1,
    replicated_by_rule: int = 0,
) -> Dict[str, int]:
    """Set ``ddlpc_hbm_bytes{kind}`` gauges from a placed TrainState;
    returns the breakdown.  Static per run layout — the trainer publishes
    once after state placement.  ``replicated_by_rule`` is
    ``StateLayout.replicated_by_rule_bytes()``: what the rule engine
    chose to keep replicated, published as its own gauge so the budget
    is explicit rather than hidden inside params/opt_state."""
    gauge = registry.gauge(
        "ddlpc_hbm_bytes",
        "Per-device resident state bytes from shape x committed sharding "
        "(grads = optimizer-boundary gradient, 1/N chunks under "
        "zero2/zero3; grads_accum = full fp32 backward accumulator, "
        "every layout).",
        labelnames=("kind",),
    )
    breakdown = state_hbm_bytes(state, level, n_shards)
    for kind, nbytes in breakdown.items():
        gauge.set(float(nbytes), kind=kind)
    registry.gauge(
        "ddlpc_hbm_replicated_by_rule_bytes",
        "Per-device bytes the partition-rule engine decided to keep "
        "replicated (uneven GSPMD dims, reason='replicated-by-rule') — "
        "the sharding contract's budgeted fallback.",
    ).set(float(replicated_by_rule))
    return breakdown
