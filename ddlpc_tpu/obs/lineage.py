"""Model lineage: the provenance record a checkpoint carries to serving.

The paper's correctness story is bit-identical replicated training — so
"which training run and step produced these logits?" must be answerable
for every served response.  A **lineage record** is stamped into each
checkpoint at save (manifest v3 + sidecar), rides restore →
``serve/engine`` reload → ``/healthz`` → the ``X-DDLPC-Model-Step``
response header → router spans and cache keys, and keys the merged
train→serve timeline in ``obs/merge.py``.

The record (a small dict — nested form lives only in manifests/sidecars
and HTTP JSON; JSONL streams carry its fields FLAT per the schema.py
contract):

- ``lineage_id``   16-hex id unique to one (run, save) — the join key;
- ``run_id``       16-hex id unique to one Trainer construction;
- ``step``         the optimizer step the checkpoint snapshots;
- ``config_hash``  sha256[:16] of the experiment config JSON;
- ``fingerprint``  sha256[:16] over the package's own source tree — the
  git-sha-equivalent for deployments without a ``.git``;
- ``saved_at``     wall-clock seconds when the save was stamped (the
  anchor for ``ddlpc_serve_model_age_s`` / ``ddlpc_deploy_latency_s``).

Checkpoints that predate lineage (v1 monolithic, v2 ``.dwc``) degrade to
:func:`unknown_lineage` — an explicit ``lineage_unknown`` marker in every
field, NEVER a crash and never a silent absence: downstream gauges skip
unknown replicas instead of reporting a fake age.

Stdlib-only by charter (analysis/tiers.py): the router's freshness gauge
reads checkpoint sidecars via :func:`newest_checkpoint_lineage` without
importing the jax-tier checkpoint reader.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
import uuid
from typing import Optional

# The explicit degradation marker.  String-typed on purpose: it shows up
# verbatim in healthz payloads, response headers and gauges' absence
# logic, so "we do not know" is distinguishable from any real value.
LINEAGE_UNKNOWN = "lineage_unknown"

# Response header carrying the serving checkpoint step end-to-end
# (replica -> router -> fleet front door), so a client — and the prod
# soak's sampler — can attribute any response to a training step.
MODEL_STEP_HEADER = "X-DDLPC-Model-Step"

# The fields every lineage record carries (schema for docs + tests).
LINEAGE_FIELDS = (
    "lineage_id",
    "run_id",
    "step",
    "config_hash",
    "fingerprint",
    "saved_at",
)

_CKPT_SIDECAR_RE = re.compile(r"^ckpt_(\d+)\.json$")

_fingerprint_cache: Optional[str] = None


def new_id() -> str:
    """16 lowercase hex chars — run ids and lineage ids."""
    return uuid.uuid4().hex[:16]


def config_hash(config_json: str) -> str:
    """sha256[:16] of a config's JSON text — two runs with the same hash
    trained under the same experiment configuration."""
    return hashlib.sha256(config_json.encode()).hexdigest()[:16]


def code_fingerprint() -> str:
    """sha256[:16] over the package's own ``*.py`` tree (sorted relpath +
    content) — a git-sha equivalent that works in deployments without a
    ``.git`` directory.  Computed once per process (the tree does not
    change under a running trainer)."""
    global _fingerprint_cache
    if _fingerprint_cache is not None:
        return _fingerprint_cache
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue  # racing editor/packaging — fingerprint best-effort
            h.update(rel.encode())
            h.update(b"\x00")
            h.update(data)
            h.update(b"\x00")
    _fingerprint_cache = h.hexdigest()[:16]
    return _fingerprint_cache


def make_lineage(
    step: int,
    run_id: Optional[str] = None,
    config_hash_hex: Optional[str] = None,
) -> dict:
    """A fresh lineage record for a checkpoint about to be saved.

    ``saved_at`` is stamped here and re-stamped by ``save_snapshot`` at
    the durable-write moment — the freshness gauges anchor on the value
    the checkpoint actually carries."""
    return {
        "lineage_id": new_id(),
        "run_id": run_id or LINEAGE_UNKNOWN,
        "step": int(step),
        "config_hash": config_hash_hex or LINEAGE_UNKNOWN,
        "fingerprint": code_fingerprint(),
        "saved_at": time.time(),
    }


def unknown_lineage(step: Optional[int] = None) -> dict:
    """The degradation record for pre-lineage checkpoints: every identity
    field is the explicit ``lineage_unknown`` marker, ``saved_at`` is None
    (no fake timestamps — age gauges SKIP, not lie).  ``step`` is kept
    when the caller knows it (the filename encodes it even for v1)."""
    return {
        "lineage_id": LINEAGE_UNKNOWN,
        "run_id": LINEAGE_UNKNOWN,
        "step": int(step) if step is not None else None,
        "config_hash": LINEAGE_UNKNOWN,
        "fingerprint": LINEAGE_UNKNOWN,
        "saved_at": None,
    }


def is_unknown(lineage: Optional[dict]) -> bool:
    """True when ``lineage`` is absent or the degradation marker."""
    return (
        not isinstance(lineage, dict)
        or lineage.get("lineage_id") in (None, LINEAGE_UNKNOWN)
    )


def flatten(lineage: Optional[dict], prefix: str = "lineage_") -> dict:
    """Flat-schema projection of a lineage record for JSONL emitters and
    healthz payloads: ``{lineage_id, lineage_run_id, ...}`` — scalars
    only, per the obs/schema.py stream contract.  ``lineage_id`` keeps
    its natural name (no ``lineage_lineage_id``)."""
    src = lineage if isinstance(lineage, dict) else unknown_lineage()
    out = {}
    for field in LINEAGE_FIELDS:
        key = field if field == "lineage_id" else prefix + field
        out[key] = src.get(field)
    return out


def newest_checkpoint_lineage(workdir: str) -> Optional[dict]:
    """Lineage of the newest checkpoint under ``workdir/checkpoints``,
    read from the JSON sidecar — stdlib-only, so the jax-free router tier
    can compute model-age against the newest DURABLE checkpoint without
    importing the checkpoint reader.  Returns None when there are no
    checkpoints; returns :func:`unknown_lineage` (with the step) when the
    newest sidecar predates lineage or is unreadable."""
    ckpt_dir = os.path.join(workdir, "checkpoints")
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    steps = sorted(
        int(m.group(1))
        for m in (_CKPT_SIDECAR_RE.match(n) for n in names)
        if m
    )
    if not steps:
        return None
    step = steps[-1]
    try:
        with open(os.path.join(ckpt_dir, f"ckpt_{step}.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return unknown_lineage(step)
    lin = meta.get("lineage")
    if not isinstance(lin, dict):
        return unknown_lineage(step)
    return dict(lin, step=lin.get("step", step))
