"""On-demand ``jax.profiler`` capture → committed-format top-ops report.

Two live triggers share this module (plus the offline scripts via
obs/xplane.py):

- the Trainer installs SIGUSR2 → :meth:`OnDemandProfiler.arm`, and its
  step loop drives :meth:`OnDemandProfiler.step_done` — the capture spans
  exactly N dispatched steps, ends with a device sync so the async
  pipeline's queued work is actually in the trace, and the aggregated
  report lands next to the run's metrics;
- the serve frontend's ``/debug/trace?steps=N`` route uses
  :func:`capture` directly around its forward counter.

Failure discipline: profiling is diagnostics, never the run's critical
path.  A backend that cannot trace, a second concurrent capture, or a
missing xplane proto all degrade to an ``error`` field in the returned
report — they never raise into the training loop or the request handler.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from ddlpc_tpu.obs import xplane as _xplane

# One capture at a time per process: jax.profiler supports a single active
# trace, and both the trainer trigger and the serve endpoint may live in
# one process (tests do exactly that).
_capture_lock = threading.Lock()


class CaptureBusy(RuntimeError):
    """Another profiler capture is already running in this process."""


def aggregate(trace_dir: str, steps: int, top: int = 30, tag: str = "") -> dict:
    """Top-ops report for a finished trace; xplane unavailability becomes
    a report-level ``error`` (the raw trace stays on disk either way)."""
    try:
        return _xplane.top_ops_report(trace_dir, top=top, steps=steps, tag=tag)
    except Exception as e:
        # Not just XplaneUnavailable/FileNotFoundError: a truncated .pb
        # (protobuf DecodeError) or any parser surprise must also degrade
        # — this function runs on the training thread via step_done().
        return {
            "tag": tag,
            "trace_dir": os.path.abspath(trace_dir),
            "steps_traced": steps,
            "error": f"{type(e).__name__}: {e}",
        }


def capture(
    trace_dir: str,
    until: Callable[[], bool],
    timeout_s: float = 30.0,
    poll_s: float = 0.01,
) -> dict:
    """Run one profiler capture until ``until()`` (or timeout); returns
    ``{"trace_dir", "seconds", "timed_out"}`` or ``{"error"}``.  Raises
    :class:`CaptureBusy` when a capture is already active."""
    import jax

    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusy("a profiler capture is already running")
    try:
        t0 = time.perf_counter()
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception as e:  # backend without profiler support
            return {"error": f"profiler failed to start: {e}"}
        timed_out = False
        try:
            deadline = t0 + timeout_s
            while not until():
                if time.perf_counter() >= deadline:
                    timed_out = True
                    break
                time.sleep(poll_s)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                return {"error": f"profiler failed to stop: {e}"}
        return {
            "trace_dir": trace_dir,
            "seconds": round(time.perf_counter() - t0, 4),
            "timed_out": timed_out,
        }
    finally:
        _capture_lock.release()


class OnDemandProfiler:
    """Arm-from-anywhere, capture-in-the-loop profiling for the Trainer.

    ``arm()`` is async-signal-safe-enough for a Python signal handler (it
    sets an Event).  The training loop calls ``step_done(sync)`` once per
    dispatched step; the profiler starts a trace on the first armed step,
    counts ``steps`` more dispatches, calls ``sync()`` (block_until_ready
    on that step's output — the async dispatch queue must drain INTO the
    trace), stops, aggregates, and writes ``top_ops_<n>.json`` +
    ``profile_<n>/`` under ``out_dir``.
    """

    def __init__(
        self,
        out_dir: str,
        steps: int = 20,
        top: int = 30,
        logger=None,
        enabled: bool = True,
    ):
        self.out_dir = out_dir
        self.steps = max(int(steps), 1)
        self.top = top
        self.logger = logger
        self.enabled = enabled
        self._armed = threading.Event()
        self._active = False
        self._steps_left = 0
        self._capture_n = 0
        self._trace_dir: Optional[str] = None
        self._t0 = 0.0
        self.last_report: Optional[dict] = None

    def arm(self, steps: Optional[int] = None) -> None:
        """Request a capture of the next ``steps`` training steps (callable
        from a signal handler or another thread)."""
        if steps is not None:
            self.steps = max(int(steps), 1)
        self._armed.set()

    @property
    def armed(self) -> bool:
        return self._armed.is_set() or self._active

    def step_done(self, sync: Optional[Callable[[], None]] = None) -> Optional[dict]:
        """Drive the capture state machine; call once per dispatched step.
        Returns the report dict when a capture completes, else None."""
        if not self.enabled:
            return None
        if self._active:
            self._steps_left -= 1
            if self._steps_left > 0:
                return None
            return self._finish(sync)
        if not self._armed.is_set():
            return None
        self._armed.clear()
        return self._start()

    def finalize(self, sync: Optional[Callable[[], None]] = None) -> Optional[dict]:
        """Close out a capture the run ended mid-way through (fewer steps
        ran than were requested): stop the trace and aggregate over the
        steps that actually happened, so the run never exits with the
        profiler left open and the arm silently lost."""
        if not self._active:
            return None
        requested = self.steps
        self.steps = max(self.steps - self._steps_left, 1)
        try:
            return self._finish(sync)
        finally:
            self.steps = requested

    # -- internals ---------------------------------------------------------

    def _start(self) -> None:
        import jax

        if not _capture_lock.acquire(blocking=False):
            self.last_report = {"error": "a profiler capture is already running"}
            return None
        self._capture_n += 1
        self._trace_dir = os.path.join(
            self.out_dir, f"profile_{self._capture_n:03d}"
        )
        os.makedirs(self._trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self._trace_dir)
        except Exception as e:
            _capture_lock.release()
            self.last_report = {"error": f"profiler failed to start: {e}"}
            return None
        self._active = True
        self._steps_left = self.steps
        self._t0 = time.perf_counter()
        return None

    def _finish(self, sync: Optional[Callable[[], None]]) -> dict:
        import jax

        try:
            sync_error = None
            if sync is not None:
                # Drain the dispatch queue into the trace: without this the
                # last steps' device work may execute after stop_trace.
                try:
                    sync()
                except Exception as e:
                    # A failed step must not leave the profiler running (the
                    # next capture would deadlock on a trace that never
                    # stops) — record the error and still stop the trace.
                    sync_error = f"sync failed: {e}"
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                self.last_report = {"error": f"profiler failed to stop: {e}"}
                return self.last_report
        finally:
            self._active = False
            _capture_lock.release()
        wall = time.perf_counter() - self._t0
        report = aggregate(
            self._trace_dir,
            steps=self.steps,
            top=self.top,
            tag=f"ondemand_{self._capture_n:03d}",
        )
        report["wall_s"] = round(wall, 4)
        report["wall_ms_per_step"] = round(wall * 1e3 / self.steps, 3)
        if sync_error is not None:
            report.setdefault("error", sync_error)
        path = os.path.join(
            self.out_dir, f"top_ops_{self._capture_n:03d}.json"
        )
        try:
            from ddlpc_tpu.utils.fsio import atomic_write_json

            atomic_write_json(path, report)
            report["report_path"] = path
        except OSError as e:  # full disk must not kill the training loop
            report.setdefault("error", f"report not written: {e}")
        self.last_report = report
        if self.logger is not None:
            try:
                self.logger.log(
                    {
                        "kind": "profile",
                        "report_path": report.get("report_path"),
                        "steps_traced": self.steps,
                        "per_step_ms": report.get("per_step_ms"),
                        "wall_ms_per_step": report["wall_ms_per_step"],
                        "error": report.get("error"),
                    },
                    echo=False,
                )
            except Exception:
                pass  # diagnostics must not break the observed loop
        return report
