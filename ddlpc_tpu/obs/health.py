"""Health detectors: turn metric streams into structured alert records.

Three detectors cover the failure modes the ROADMAP items keep hitting in
practice — the kind a human spots by staring at metrics.jsonl after the
fact, emitted live instead:

- :class:`EwmaRegressionDetector` — step-time regression: the observed
  value exceeds ``factor`` × its own exponentially-weighted moving average
  (the standard drift-tolerant baseline: slow drift folds into the EWMA,
  a sudden regression does not);
- :class:`LossDetector` — NaN/inf loss (critical, always) and loss spikes
  against the same EWMA logic;
- :class:`QueueSaturationDetector` — the serve admission queue sitting at
  ≥ ``threshold`` of its limit for ``consecutive`` observations (a single
  full sample is a burst; a sustained one means shedding is imminent).

Alerts are plain flat records (``kind="alert"``) published by the
:class:`HealthMonitor` into the run's JSONL metrics stream, the Prometheus
registry (``ddlpc_alerts_total{alert,severity}``), and the
``StallWatchdog``'s recent-alert ring — so a stall diagnosis shows what
health was doing just before the hang.  Detection never raises into the
loop being observed.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ddlpc_tpu.analysis import lockcheck


@dataclass
class Alert:
    """One structured health alert.  ``record()`` is the flat JSONL form."""

    alert: str  # detector kind, e.g. "step_time_regression"
    severity: str  # "warn" | "critical"
    message: str
    value: float
    threshold: float
    context: Dict[str, object] = field(default_factory=dict)

    def record(self) -> Dict[str, object]:
        rec: Dict[str, object] = {
            "kind": "alert",
            "alert": self.alert,
            "severity": self.severity,
            "message": self.message,
            "value": None if math.isnan(self.value) else round(self.value, 6),
            "threshold": round(self.threshold, 6),
        }
        rec.update(self.context)
        return rec


class EwmaRegressionDetector:
    """Fires when an observation exceeds ``factor`` × the EWMA of previous
    observations.  The first ``warmup`` observations only seed the average
    (compile-time first steps must not count as regressions); the alerting
    observation still updates the EWMA, so a sustained new plateau stops
    alerting once the average catches up (level shift, not a siren)."""

    def __init__(
        self,
        kind: str = "step_time_regression",
        factor: float = 1.5,
        alpha: float = 0.2,
        warmup: int = 5,
        severity: str = "warn",
    ):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        self.kind = kind
        self.factor = float(factor)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.severity = severity
        self._ewma: Optional[float] = None
        self._seen = 0

    def observe(self, value: float) -> Optional[Alert]:
        v = float(value)
        if math.isnan(v) or math.isinf(v):
            return None  # not this detector's failure mode
        alert = None
        if self._seen >= self.warmup and self._ewma is not None:
            limit = self.factor * self._ewma
            if v > limit and self._ewma > 0:
                alert = Alert(
                    alert=self.kind,
                    severity=self.severity,
                    message=(
                        f"{self.kind}: {v:.4g} > {self.factor:.2f}x "
                        f"EWMA {self._ewma:.4g}"
                    ),
                    value=v,
                    threshold=limit,
                    context={"ewma": round(self._ewma, 6)},
                )
        self._ewma = (
            v
            if self._ewma is None
            else (1 - self.alpha) * self._ewma + self.alpha * v
        )
        self._seen += 1
        return alert


class LossDetector:
    """NaN/inf loss → critical alert (always, every observation — a NaN
    loss means the run is dead and the record should say so repeatedly);
    finite spikes ride the EWMA regression logic."""

    def __init__(self, factor: float = 2.0, alpha: float = 0.3, warmup: int = 3):
        self._ewma = EwmaRegressionDetector(
            kind="loss_spike", factor=factor, alpha=alpha, warmup=warmup
        )

    def observe(self, loss: float) -> Optional[Alert]:
        v = float(loss)
        if math.isnan(v) or math.isinf(v):
            return Alert(
                alert="loss_nonfinite",
                severity="critical",
                message=f"loss is {v!r}: the optimization has diverged",
                value=v,
                threshold=0.0,
            )
        return self._ewma.observe(v)


class QueueSaturationDetector:
    """Sustained queue saturation: depth/limit ≥ ``threshold`` for
    ``consecutive`` observations fires once, then holds until the queue
    drops below the threshold (re-arms on recovery — no alert-per-scrape
    spam while saturated)."""

    def __init__(self, threshold: float = 0.9, consecutive: int = 3):
        if not 0.0 < threshold <= 1.0:
            raise ValueError(f"threshold must be in (0, 1], got {threshold}")
        self.threshold = float(threshold)
        self.consecutive = int(consecutive)
        self._streak = 0
        self._latched = False

    def observe(self, depth: int, limit: int) -> Optional[Alert]:
        ratio = depth / max(int(limit), 1)
        if ratio < self.threshold:
            self._streak = 0
            self._latched = False
            return None
        self._streak += 1
        if self._streak < self.consecutive or self._latched:
            return None
        self._latched = True
        return Alert(
            alert="queue_saturation",
            severity="warn",
            message=(
                f"admission queue at {depth}/{limit} "
                f"({ratio:.0%}) for {self._streak} consecutive samples — "
                f"shedding imminent"
            ),
            value=ratio,
            threshold=self.threshold,
            context={"queue_depth": int(depth), "queue_limit": int(limit)},
        )


@lockcheck.guarded
class HealthMonitor:
    """Owns the detectors for one process side and fans alerts out to the
    JSONL stream, the metrics registry, and the stall watchdog."""

    def __init__(
        self,
        logger=None,
        registry=None,
        watchdog=None,
        service: str = "train",
        step_time_factor: float = 1.5,
        loss_factor: float = 2.0,
        queue_threshold: float = 0.9,
        max_kept: int = 64,
    ):
        self.logger = logger
        self.watchdog = watchdog
        self.service = service
        # Appended by the observing thread, snapshotted by HTTP handler
        # threads (/healthz) — same discipline as the watchdog's ring:
        # mutation and iteration under one lock, or CPython raises
        # "deque mutated during iteration" into a scrape.
        self._alerts: deque = deque(maxlen=max_kept)  # guarded-by: _alerts_lock
        self._alerts_lock = lockcheck.lock("HealthMonitor._alerts_lock")
        self._step_time = EwmaRegressionDetector(factor=step_time_factor)
        self._loss = LossDetector(factor=loss_factor)
        self._queue = QueueSaturationDetector(threshold=queue_threshold)
        self._counter = (
            registry.counter(
                "ddlpc_alerts_total",
                "Health alerts emitted, by detector and severity.",
                labelnames=("alert", "severity"),
            )
            if registry is not None
            else None
        )

    @property
    def alerts(self) -> List[Dict[str, object]]:
        """Snapshot of the recent alert records (thread-safe)."""
        with self._alerts_lock:
            return list(self._alerts)

    def emit(self, alert: Alert) -> Dict[str, object]:
        rec = alert.record()
        rec["service"] = self.service
        rec.setdefault("time", time.time())
        with self._alerts_lock:
            self._alerts.append(rec)
        if self._counter is not None:
            self._counter.inc(alert=alert.alert, severity=alert.severity)
        if self.watchdog is not None:
            try:
                self.watchdog.record_alert(rec)
            except Exception:
                pass  # diagnostics must not break the observed loop
        if self.logger is not None:
            try:
                self.logger.log(rec, echo=alert.severity == "critical")
            except Exception:
                pass
        return rec

    def observe_train(self, record: Dict[str, object]) -> List[Alert]:
        """Feed one epoch/step metrics record; emits and returns alerts."""
        out: List[Alert] = []
        loss = record.get("loss")
        if isinstance(loss, (int, float)):
            a = self._loss.observe(loss)
            if a is not None:
                out.append(a)
        st = record.get("step_time_s")
        if isinstance(st, (int, float)):
            a = self._step_time.observe(st)
            if a is not None:
                out.append(a)
        for a in out:
            self.emit(a)
        return out

    def observe_queue(self, depth: int, limit: int) -> Optional[Alert]:
        """Feed one serve queue-depth sample; emits and returns the alert."""
        a = self._queue.observe(depth, limit)
        if a is not None:
            self.emit(a)
        return a


# ---------------------------------------------------------------------------
# SLO tracking: error budgets + multi-window burn-rate alerts (ISSUE 14)
# ---------------------------------------------------------------------------


class _WindowCounter:
    """Sliding-window good/bad accounting at O(1) time and bounded
    memory: events aggregate into ``window_s / buckets`` coarse time
    buckets, so observation is an increment on the tail bucket and
    eviction pops fully-expired buckets from the head.  The window is
    honored to within one bucket (default window/60) — burn-rate
    alerting needs nothing finer, and the alternative (a raw event
    deque) puts a full-window walk under the SLO lock on every
    ``/healthz`` scrape, stalling the dispatch threads whose p99 the SLO
    is measuring."""

    __slots__ = ("window_s", "res", "_q", "bad", "total")

    def __init__(self, window_s: float, buckets: int = 60):
        self.window_s = float(window_s)
        self.res = self.window_s / max(int(buckets), 1)
        self._q: deque = deque()  # [bucket_index, bad, total]
        self.bad = 0
        self.total = 0

    def add(self, now: float, good: bool) -> None:
        b = int(now // self.res)
        if self._q and self._q[-1][0] == b:
            e = self._q[-1]
        else:
            e = [b, 0, 0]
            self._q.append(e)
        if not good:
            e[1] += 1
            self.bad += 1
        e[2] += 1
        self.total += 1
        self.evict(now)

    def evict(self, now: float) -> None:
        # a bucket leaves only once ALL its events are older than the
        # cutoff (conservative: the window runs at most one bucket long)
        cutoff = now - self.window_s
        q = self._q
        while q and (q[0][0] + 1) * self.res <= cutoff:
            _, bad, total = q.popleft()
            self.bad -= bad
            self.total -= total

    def counts(self, now: float) -> Tuple[int, int]:
        self.evict(now)
        return self.bad, self.total


class BurnRateLatch:
    """One (window, threshold) burn-rate alarm with the latch/re-arm shape
    of :class:`QueueSaturationDetector`: fires ONCE when the burn rate
    reaches ``threshold``, stays quiet while it remains there (no
    alert-per-evaluation spam), re-arms when the rate drops below."""

    def __init__(self, label: str, window_s: float, threshold: float,
                 severity: str):
        if threshold <= 0:
            raise ValueError(f"burn threshold must be > 0, got {threshold}")
        self.label = label
        self.window_s = float(window_s)
        self.threshold = float(threshold)
        self.severity = severity
        self._latched = False

    def observe(self, burn_rate: float) -> bool:
        """True exactly when this evaluation should alert."""
        if burn_rate < self.threshold:
            self._latched = False
            return False
        if self._latched:
            return False
        self._latched = True
        return True


@lockcheck.guarded
class SLOTracker:
    """Per-priority-class latency/availability objectives over sliding
    windows — the fleet router feeds it one (priority, latency, ok) per
    routed request.

    A request is GOOD when it succeeded (no 5xx) AND finished inside its
    class's latency objective.  The availability objective says what
    fraction must be good; the error budget over ``budget_window_s`` is
    the allowed bad fraction, and a *burn rate* is (observed bad fraction)
    / (allowed bad fraction) over a window — burn 1.0 spends the budget
    exactly at its window's length, burn 14 torches it 14× faster.  Two
    windows (the multi-window pattern from the SRE literature) catch both
    a fast outage and a slow leak; each is a :class:`BurnRateLatch`.

    Everything is published three ways: ``ddlpc_slo_*`` registry series,
    one flat ``kind="slo"`` record per :meth:`status` call (the router's
    emit cadence), and alerts through a :class:`HealthMonitor`.
    Thread-safe; observation AND evaluation are O(1) — windows are
    time-bucketed (:class:`_WindowCounter`, resolution window/60), so a
    ``/healthz`` scrape never walks an event log under the lock the
    dispatch threads need.
    """

    def __init__(
        self,
        latency_objectives_s: Dict[str, float],
        availability: float = 0.999,
        budget_window_s: float = 3600.0,
        windows: Optional[List[Tuple[str, float, float, str]]] = None,
        min_requests: int = 10,
        registry=None,
        monitor: Optional[HealthMonitor] = None,
        clock=time.monotonic,
        enabled: bool = True,
    ):
        if not 0.0 < availability < 1.0:
            # availability 1.0 would make every burn rate infinite; an SLO
            # of "never fail" is not an SLO, it is a wish.
            if enabled:
                raise ValueError(
                    f"availability objective must be in (0, 1), got "
                    f"{availability}"
                )
        self.enabled = bool(enabled) and bool(latency_objectives_s)
        self.objectives = {
            str(k): float(v) for k, v in latency_objectives_s.items()
        }
        self.availability = float(availability)
        self.budget_window_s = float(budget_window_s)
        self.windows = list(
            windows
            if windows is not None
            else [
                ("fast", 300.0, 14.0, "critical"),
                ("slow", 3600.0, 2.0, "warn"),
            ]
        )
        self.min_requests = int(min_requests)
        self._clock = clock
        self._monitor = monitor
        self._lock = lockcheck.lock("SLOTracker._lock")
        self._t0 = clock()
        # per priority class, one bucketed counter per distinct window
        # (latch windows + the budget window, deduped by length)
        window_lengths = sorted(
            {self.budget_window_s} | {w[1] for w in self.windows}
        )
        self._wins: dict = {
            p: {w: _WindowCounter(w) for w in window_lengths}
            for p in self.objectives
        }  # guarded-by: _lock
        self._latches: dict = {
            p: [BurnRateLatch(lbl, w, thr, sev)
                for lbl, w, thr, sev in self.windows]
            for p in self.objectives
        }  # guarded-by: _lock
        self._reg = None
        if registry is not None and self.enabled:
            self._reg = {
                "requests": registry.counter(
                    "ddlpc_slo_requests_total",
                    "Routed requests classified against the SLO, by "
                    "priority class and good/bad.",
                    labelnames=("priority", "good"),
                ),
                "budget": registry.gauge(
                    "ddlpc_slo_error_budget_remaining",
                    "Fraction of the error budget left over the budget "
                    "window, by priority class (1 = untouched, 0 = spent, "
                    "negative = overspent).",
                    labelnames=("priority",),
                ),
                "burn": registry.gauge(
                    "ddlpc_slo_burn_rate",
                    "Error-budget burn rate by priority class and "
                    "alerting window (1.0 = spending exactly at budget).",
                    labelnames=("priority", "window"),
                ),
            }

    @classmethod
    def from_fleet_config(cls, cfg, registry=None,
                          monitor: Optional[HealthMonitor] = None,
                          clock=time.monotonic) -> "SLOTracker":
        """The fleet wiring: objectives + windows from ``FleetConfig``
        ``slo_*`` knobs (config.py documents each)."""
        return cls(
            latency_objectives_s={
                "interactive": cfg.slo_interactive_p99_ms / 1000.0,
                "batch": cfg.slo_batch_p99_ms / 1000.0,
            },
            availability=cfg.slo_availability,
            budget_window_s=cfg.slo_budget_window_s,
            windows=[
                ("fast", cfg.slo_fast_window_s, cfg.slo_fast_burn,
                 "critical"),
                ("slow", cfg.slo_slow_window_s, cfg.slo_slow_burn, "warn"),
            ],
            registry=registry,
            monitor=monitor,
            clock=clock,
            enabled=cfg.slo_enabled,
        )

    # -- feeding -------------------------------------------------------------

    def observe(self, priority: str, latency_s: float, ok: bool,
                now: Optional[float] = None) -> None:
        """Classify one routed request.  Unknown priorities count against
        the interactive objective (the router's own fallback rule)."""
        if not self.enabled:
            return
        p = priority if priority in self.objectives else "interactive"
        if p not in self.objectives:
            return
        now = self._clock() if now is None else now
        good = bool(ok) and float(latency_s) <= self.objectives[p]
        with self._lock:
            for wc in self._wins[p].values():
                wc.add(now, good)
        if self._reg is not None:
            self._reg["requests"].inc(
                priority=p, good="true" if good else "false"
            )

    # -- evaluation ----------------------------------------------------------

    def _window_counts(self, p: str, window_s: float,
                       now: float) -> Tuple[int, int]:
        """(bad, total) within the trailing window — an O(1) bucket-sum
        readout (at sustained load the budget window holds 100k+ events
        and an evaluation must never walk them under the lock dispatch
        threads need for observe())."""
        with self._lock:
            wc = self._wins.get(p, {}).get(window_s)
            if wc is None:
                return 0, 0
            return wc.counts(now)

    def _burn(self, bad: int, total: int) -> float:
        if total == 0:
            return 0.0  # an idle fleet burns nothing
        return (bad / total) / (1.0 - self.availability)

    def burn_rate(self, priority: str, window_s: float,
                  now: Optional[float] = None) -> float:
        """(bad fraction over window) / (allowed bad fraction)."""
        now = self._clock() if now is None else now
        return self._burn(*self._window_counts(priority, window_s, now))

    @staticmethod
    def _budget_remaining_from(bad: int, total: int,
                               availability: float) -> float:
        if total == 0:
            return 1.0
        allowed = total * (1.0 - availability)
        return 1.0 - bad / allowed if allowed > 0 else 0.0

    def error_budget_remaining(self, priority: str,
                               now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        bad, total = self._window_counts(
            priority, self.budget_window_s, now
        )
        return self._budget_remaining_from(bad, total, self.availability)

    def check(self, now: Optional[float] = None) -> List[Alert]:
        """Evaluate every (class, window) burn latch; emit fired alerts
        through the health monitor (latched — one alert per excursion,
        re-armed on recovery).  Publishes the gauges as a side effect."""
        if not self.enabled:
            return []
        now = self._clock() if now is None else now
        out: List[Alert] = []
        for p in self.objectives:
            budget = self.error_budget_remaining(p, now)
            if self._reg is not None:
                self._reg["budget"].set(budget, priority=p)
            with self._lock:
                latches = list(self._latches[p])
            for latch in latches:
                bad, total = self._window_counts(p, latch.window_s, now)
                burn = self._burn(bad, total)
                if self._reg is not None:
                    self._reg["burn"].set(
                        burn, priority=p, window=latch.label
                    )
                if total < self.min_requests:
                    continue  # too little traffic to call an outage
                if latch.observe(burn):
                    out.append(
                        Alert(
                            alert=f"slo_burn_{latch.label}",
                            severity=latch.severity,
                            message=(
                                f"{p} error-budget burn rate {burn:.1f}x "
                                f"over the last {latch.window_s:.0f}s "
                                f"(threshold {latch.threshold:.1f}x, "
                                f"availability objective "
                                f"{self.availability:.4f})"
                            ),
                            value=burn,
                            threshold=latch.threshold,
                            context={
                                "priority": p,
                                "window_s": latch.window_s,
                                "error_budget_remaining": round(budget, 4),
                            },
                        )
                    )
        if self._monitor is not None:
            for a in out:
                self._monitor.emit(a)
        return out

    def status(self, now: Optional[float] = None) -> Dict[str, object]:
        """One flat ``kind="slo"`` record: the error-budget ledger per
        priority class, ready for the router's JSONL stream and the fleet
        ``/healthz``."""
        now = self._clock() if now is None else now
        rec: Dict[str, object] = {
            "kind": "slo",
            "availability_objective": self.availability,
            "budget_window_s": self.budget_window_s,
        }
        for p, obj_s in sorted(self.objectives.items()):
            bad, total = self._window_counts(p, self.budget_window_s, now)
            rec[f"{p}_latency_objective_ms"] = round(obj_s * 1000.0, 3)
            rec[f"{p}_requests"] = total
            rec[f"{p}_bad"] = bad
            rec[f"{p}_error_budget_remaining"] = round(
                self._budget_remaining_from(bad, total, self.availability), 4
            )
            for latch in self._latches[p]:
                rec[f"{p}_burn_{latch.label}"] = round(
                    self.burn_rate(p, latch.window_s, now), 4
                )
        return rec
