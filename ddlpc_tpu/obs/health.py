"""Health detectors: turn metric streams into structured alert records.

Three detectors cover the failure modes the ROADMAP items keep hitting in
practice — the kind a human spots by staring at metrics.jsonl after the
fact, emitted live instead:

- :class:`EwmaRegressionDetector` — step-time regression: the observed
  value exceeds ``factor`` × its own exponentially-weighted moving average
  (the standard drift-tolerant baseline: slow drift folds into the EWMA,
  a sudden regression does not);
- :class:`LossDetector` — NaN/inf loss (critical, always) and loss spikes
  against the same EWMA logic;
- :class:`QueueSaturationDetector` — the serve admission queue sitting at
  ≥ ``threshold`` of its limit for ``consecutive`` observations (a single
  full sample is a burst; a sustained one means shedding is imminent).

Alerts are plain flat records (``kind="alert"``) published by the
:class:`HealthMonitor` into the run's JSONL metrics stream, the Prometheus
registry (``ddlpc_alerts_total{alert,severity}``), and the
``StallWatchdog``'s recent-alert ring — so a stall diagnosis shows what
health was doing just before the hang.  Detection never raises into the
loop being observed.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ddlpc_tpu.analysis import lockcheck


@dataclass
class Alert:
    """One structured health alert.  ``record()`` is the flat JSONL form."""

    alert: str  # detector kind, e.g. "step_time_regression"
    severity: str  # "warn" | "critical"
    message: str
    value: float
    threshold: float
    context: Dict[str, object] = field(default_factory=dict)

    def record(self) -> Dict[str, object]:
        rec: Dict[str, object] = {
            "kind": "alert",
            "alert": self.alert,
            "severity": self.severity,
            "message": self.message,
            "value": None if math.isnan(self.value) else round(self.value, 6),
            "threshold": round(self.threshold, 6),
        }
        rec.update(self.context)
        return rec


class EwmaRegressionDetector:
    """Fires when an observation exceeds ``factor`` × the EWMA of previous
    observations.  The first ``warmup`` observations only seed the average
    (compile-time first steps must not count as regressions); the alerting
    observation still updates the EWMA, so a sustained new plateau stops
    alerting once the average catches up (level shift, not a siren)."""

    def __init__(
        self,
        kind: str = "step_time_regression",
        factor: float = 1.5,
        alpha: float = 0.2,
        warmup: int = 5,
        severity: str = "warn",
    ):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        self.kind = kind
        self.factor = float(factor)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.severity = severity
        self._ewma: Optional[float] = None
        self._seen = 0

    def observe(self, value: float) -> Optional[Alert]:
        v = float(value)
        if math.isnan(v) or math.isinf(v):
            return None  # not this detector's failure mode
        alert = None
        if self._seen >= self.warmup and self._ewma is not None:
            limit = self.factor * self._ewma
            if v > limit and self._ewma > 0:
                alert = Alert(
                    alert=self.kind,
                    severity=self.severity,
                    message=(
                        f"{self.kind}: {v:.4g} > {self.factor:.2f}x "
                        f"EWMA {self._ewma:.4g}"
                    ),
                    value=v,
                    threshold=limit,
                    context={"ewma": round(self._ewma, 6)},
                )
        self._ewma = (
            v
            if self._ewma is None
            else (1 - self.alpha) * self._ewma + self.alpha * v
        )
        self._seen += 1
        return alert


class LossDetector:
    """NaN/inf loss → critical alert (always, every observation — a NaN
    loss means the run is dead and the record should say so repeatedly);
    finite spikes ride the EWMA regression logic."""

    def __init__(self, factor: float = 2.0, alpha: float = 0.3, warmup: int = 3):
        self._ewma = EwmaRegressionDetector(
            kind="loss_spike", factor=factor, alpha=alpha, warmup=warmup
        )

    def observe(self, loss: float) -> Optional[Alert]:
        v = float(loss)
        if math.isnan(v) or math.isinf(v):
            return Alert(
                alert="loss_nonfinite",
                severity="critical",
                message=f"loss is {v!r}: the optimization has diverged",
                value=v,
                threshold=0.0,
            )
        return self._ewma.observe(v)


class QueueSaturationDetector:
    """Sustained queue saturation: depth/limit ≥ ``threshold`` for
    ``consecutive`` observations fires once, then holds until the queue
    drops below the threshold (re-arms on recovery — no alert-per-scrape
    spam while saturated)."""

    def __init__(self, threshold: float = 0.9, consecutive: int = 3):
        if not 0.0 < threshold <= 1.0:
            raise ValueError(f"threshold must be in (0, 1], got {threshold}")
        self.threshold = float(threshold)
        self.consecutive = int(consecutive)
        self._streak = 0
        self._latched = False

    def observe(self, depth: int, limit: int) -> Optional[Alert]:
        ratio = depth / max(int(limit), 1)
        if ratio < self.threshold:
            self._streak = 0
            self._latched = False
            return None
        self._streak += 1
        if self._streak < self.consecutive or self._latched:
            return None
        self._latched = True
        return Alert(
            alert="queue_saturation",
            severity="warn",
            message=(
                f"admission queue at {depth}/{limit} "
                f"({ratio:.0%}) for {self._streak} consecutive samples — "
                f"shedding imminent"
            ),
            value=ratio,
            threshold=self.threshold,
            context={"queue_depth": int(depth), "queue_limit": int(limit)},
        )


@lockcheck.guarded
class HealthMonitor:
    """Owns the detectors for one process side and fans alerts out to the
    JSONL stream, the metrics registry, and the stall watchdog."""

    def __init__(
        self,
        logger=None,
        registry=None,
        watchdog=None,
        service: str = "train",
        step_time_factor: float = 1.5,
        loss_factor: float = 2.0,
        queue_threshold: float = 0.9,
        max_kept: int = 64,
    ):
        self.logger = logger
        self.watchdog = watchdog
        self.service = service
        # Appended by the observing thread, snapshotted by HTTP handler
        # threads (/healthz) — same discipline as the watchdog's ring:
        # mutation and iteration under one lock, or CPython raises
        # "deque mutated during iteration" into a scrape.
        self._alerts: deque = deque(maxlen=max_kept)  # guarded-by: _alerts_lock
        self._alerts_lock = lockcheck.lock("HealthMonitor._alerts_lock")
        self._step_time = EwmaRegressionDetector(factor=step_time_factor)
        self._loss = LossDetector(factor=loss_factor)
        self._queue = QueueSaturationDetector(threshold=queue_threshold)
        self._counter = (
            registry.counter(
                "ddlpc_alerts_total",
                "Health alerts emitted, by detector and severity.",
                labelnames=("alert", "severity"),
            )
            if registry is not None
            else None
        )

    @property
    def alerts(self) -> List[Dict[str, object]]:
        """Snapshot of the recent alert records (thread-safe)."""
        with self._alerts_lock:
            return list(self._alerts)

    def emit(self, alert: Alert) -> Dict[str, object]:
        rec = alert.record()
        rec["service"] = self.service
        rec.setdefault("time", time.time())
        with self._alerts_lock:
            self._alerts.append(rec)
        if self._counter is not None:
            self._counter.inc(alert=alert.alert, severity=alert.severity)
        if self.watchdog is not None:
            try:
                self.watchdog.record_alert(rec)
            except Exception:
                pass  # diagnostics must not break the observed loop
        if self.logger is not None:
            try:
                self.logger.log(rec, echo=alert.severity == "critical")
            except Exception:
                pass
        return rec

    def observe_train(self, record: Dict[str, object]) -> List[Alert]:
        """Feed one epoch/step metrics record; emits and returns alerts."""
        out: List[Alert] = []
        loss = record.get("loss")
        if isinstance(loss, (int, float)):
            a = self._loss.observe(loss)
            if a is not None:
                out.append(a)
        st = record.get("step_time_s")
        if isinstance(st, (int, float)):
            a = self._step_time.observe(st)
            if a is not None:
                out.append(a)
        for a in out:
            self.emit(a)
        return out

    def observe_queue(self, depth: int, limit: int) -> Optional[Alert]:
        """Feed one serve queue-depth sample; emits and returns the alert."""
        a = self._queue.observe(depth, limit)
        if a is not None:
            self.emit(a)
        return a
