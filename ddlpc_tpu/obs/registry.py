"""Prometheus-style metrics registry: Counter / Gauge / Histogram + labels.

One :class:`MetricsRegistry` per process side (train, serve); both
``MetricsLogger`` (train/observability.py) and ``ServeMetrics``
(serve/metrics.py) publish into it, and the HTTP layers expose it as
Prometheus text exposition (content-negotiated on the serve ``/metrics``
route; a dedicated telemetry endpoint for training — obs/http.py).

Deliberately small and dependency-free — the subset of the Prometheus data
model this repo needs, not a client library:

- metric types: counter (monotonic), gauge (set/inc/dec), histogram
  (cumulative ``le`` buckets + ``_sum``/``_count``);
- labels: declared per metric (``labelnames``), passed as kwargs on every
  update; each distinct label-value tuple is an independent series;
- registration is idempotent: asking for an existing (name, type,
  labelnames) returns the existing metric, a conflicting redeclaration
  raises — so subsystems can declare their metrics where they use them;
- exposition follows the text format v0.0.4 (``# HELP``/``# TYPE`` then
  one ``name{labels} value`` line per series).

Thread-safe: all mutation goes through one registry lock (updates are
dict/float ops — contention is negligible next to the work being measured).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-oriented default buckets (seconds), Prometheus' classic set.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def sanitize_name(name: str) -> str:
    """A valid Prometheus metric name from an arbitrary record key."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not name or not _NAME_RE.match(name):
        name = "_" + name
    return name


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str],
        lock: threading.Lock,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._series: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: dict) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _series_suffix(self, key: Tuple[str, ...], extra: str = "") -> str:
        pairs = [
            f'{ln}="{_escape_label(lv)}"'
            for ln, lv in zip(self.labelnames, key)
        ]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def expose(self) -> List[str]:
        raise NotImplementedError

    def header(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        return lines


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [
            f"{self.name}{self._series_suffix(k)} {_fmt(v)}"
            for k, v in items
        ]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [
            f"{self.name}{self._series_suffix(k)} {_fmt(v)}"
            for k, v in items
        ]


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = bs  # +Inf is implicit

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = self._series[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            i = len(self.buckets)
            for j, b in enumerate(self.buckets):
                if v <= b:
                    i = j
                    break
            st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(
                (k, [list(v["counts"]), v["sum"], v["count"]])
                for k, v in self._series.items()
            )
        lines = []
        for key, (counts, total, count) in items:
            cum = 0
            for b, c in zip((*self.buckets, float("inf")), counts):
                cum += c
                le = self._series_suffix(key, extra=f'le="{_fmt(b)}"')
                lines.append(f"{self.name}_bucket{le} {cum}")
            lines.append(f"{self.name}_sum{self._series_suffix(key)} {_fmt(total)}")
            lines.append(f"{self.name}_count{self._series_suffix(key)} {count}")
        return lines


class MetricsRegistry:
    """Get-or-create metric factory + text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}"
                    )
                return existing
            # Metrics share the registry lock: updates are tiny dict ops and
            # one lock keeps exposition consistent without lock ordering.
            m = cls(name, help, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def exposition(self) -> str:
        """Prometheus text exposition format v0.0.4 for every metric."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines.extend(m.header())
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, object]:
        """Flat JSON view: one key per series (``name{l="v"}`` for labeled
        series), histograms reduced to ``_sum``/``_count``."""
        out: Dict[str, object] = {}
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if isinstance(m, Histogram):
                with self._lock:
                    items = sorted(self._series_copy(m).items())
                for key, st in items:
                    sfx = m._series_suffix(key)
                    out[f"{m.name}_sum{sfx}"] = st["sum"]
                    out[f"{m.name}_count{sfx}"] = st["count"]
            else:
                with self._lock:
                    items = sorted(m._series.items())
                for key, v in items:
                    out[f"{m.name}{m._series_suffix(key)}"] = v
        return out

    @staticmethod
    def _series_copy(m: Histogram) -> dict:
        return {k: dict(v) for k, v in m._series.items()}
