"""The one-flat-JSON-object-per-line record contract, in code.

Every JSONL stream in the repo — ``metrics.jsonl``, ``serve_metrics.jsonl``,
``spans.jsonl``, ``serve_spans.jsonl``, ``resilience.jsonl`` — carries
records of this shape, so
one tool (``scripts/obs_tail.py``) tails any of them and one lint
(``scripts/check_metrics_schema.py``, invoked from tier-1) keeps emitters
honest.  :func:`check_record` is the single owner of what "flat" means.
"""

from __future__ import annotations

from typing import List

# Version of the flat-JSONL record schema.  Bump ONLY on a breaking shape
# change (a record stops being one flat JSON object per line); adding keys
# is not a bump.
SCHEMA_VERSION = 1

_SCALAR = (str, int, float, bool, type(None))


def check_record(obj: object) -> List[str]:
    """Violations of the stream contract for one decoded JSONL record.

    A conforming record is a JSON object whose values are scalars or lists
    of scalars (``val_iou_per_class`` is a list), carrying an integer
    ``schema`` field.  Returns human-readable violation strings; empty
    means conforming.
    """
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"record is {type(obj).__name__}, not a JSON object"]
    schema = obj.get("schema")
    if schema is None:
        errs.append("missing 'schema' field")
    elif not isinstance(schema, int) or isinstance(schema, bool):
        errs.append(f"'schema' must be an integer, got {schema!r}")
    for k, v in obj.items():
        if isinstance(v, _SCALAR):
            continue
        if isinstance(v, list) and all(isinstance(x, _SCALAR) for x in v):
            continue
        errs.append(
            f"key {k!r} holds a {type(v).__name__} — records must stay flat "
            f"(scalars or lists of scalars)"
        )
    return errs
