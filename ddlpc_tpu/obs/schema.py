"""The one-flat-JSON-object-per-line record contract, in code.

Every JSONL stream in the repo — ``metrics.jsonl``, ``serve_metrics.jsonl``,
``spans.jsonl``, ``serve_spans.jsonl``, ``resilience.jsonl`` — carries
records of this shape, so
one tool (``scripts/obs_tail.py``) tails any of them and one lint
(``scripts/check_metrics_schema.py``, invoked from tier-1) keeps emitters
honest.  :func:`check_record` is the single owner of what "flat" means.
"""

from __future__ import annotations

import time
from typing import List, Optional

# Version of the flat-JSONL record schema.  Bump ONLY on a breaking shape
# change (a record stops being one flat JSON object per line); adding keys
# is not a bump.
SCHEMA_VERSION = 1

# Every record ``kind`` the repo's emitters stamp (records without a
# ``kind`` are training metrics, kind "train").  The lint rejects unknown
# kinds so a typo'd emitter cannot silently fork a new stream dialect;
# new subsystems register their kinds here first.
KNOWN_KINDS = frozenset(
    {
        "train",  # per-epoch training metrics (the kind-less default)
        "span",  # tracer (obs/tracing.py)
        "alert",  # health detectors (obs/health.py)
        "serve",  # serve metrics snapshots (serve/metrics.py)
        "serve_reload",  # hot-reload audit records (serve/server.py)
        "serve_quant",  # quantized-deploy audit: mode + resident bytes (serve/server.py)
        "profile",  # on-demand profiler reports (obs/profiling.py)
        "preempt",  # graceful-preemption record (train/trainer.py)
        "supervisor_attempt",  # resilience.jsonl (resilience/supervisor.py)
        "supervisor_give_up",
        "perf",  # goodput/MFU accounting (obs/flops.py, per epoch)
        "comm",  # communication accounting (obs/comm.py)
        "router",  # fleet router snapshots/events — router.jsonl (serve/router.py)
        "fleet",  # replica supervision events — router.jsonl (serve/fleet.py)
        "analysis",  # static-analysis reports — analysis.jsonl (scripts/ddlpc_check.py)
        "program",  # compiled-program audits — programs.jsonl (scripts/program_audit.py)
        "slo",  # error-budget ledger — router.jsonl (obs/health.py:SLOTracker)
        "fleet_trace",  # per-request cross-process attribution (obs/merge.py, scripts/fleet_report.py)
        "autoscale",  # elastic-fleet policy decisions — router.jsonl (serve/autoscale.py)
        "cache",  # response-cache stats snapshots — router.jsonl (serve/cache.py)
        "lineage",  # checkpoint provenance events — metrics.jsonl/router.jsonl (obs/lineage.py consumers)
        "prod_soak",  # train-to-serve soak audit records (scripts/prod_soak.py)
        "pipeline",  # pipeline A/B rows — docs/sharding/pipeline_ab.json (bench.py --pipeline-ab)
    }
)

_SCALAR = (str, int, float, bool, type(None))


def check_record(obj: object) -> List[str]:
    """Violations of the stream contract for one decoded JSONL record.

    A conforming record is a JSON object whose values are scalars or lists
    of scalars (``val_iou_per_class`` is a list), carrying an integer
    ``schema`` field at or below :data:`SCHEMA_VERSION` and (when present)
    a ``kind`` from :data:`KNOWN_KINDS`.  Records from OLDER schema
    versions are tolerated (long-lived runs survive an in-place tooling
    upgrade — :func:`is_stale` lets tools count and report them); records
    claiming a NEWER version than this tooling understands are violations.
    Returns human-readable violation strings; empty means conforming.
    """
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"record is {type(obj).__name__}, not a JSON object"]
    schema = obj.get("schema")
    if schema is None:
        errs.append("missing 'schema' field")
    elif not isinstance(schema, int) or isinstance(schema, bool):
        errs.append(f"'schema' must be an integer, got {schema!r}")
    elif schema > SCHEMA_VERSION:
        errs.append(
            f"'schema' {schema} is newer than this tooling's "
            f"SCHEMA_VERSION {SCHEMA_VERSION} — upgrade the tooling"
        )
    elif schema < 0:
        # Versions start at 1 (0 grandfathers pre-stamp records); a
        # negative stamp is an emitter bug, not an old version.
        errs.append(f"'schema' {schema} is not a valid version")
    kind = obj.get("kind")
    if kind is not None and (
        not isinstance(kind, str) or kind not in KNOWN_KINDS
    ):
        errs.append(
            f"unknown record kind {kind!r} — register it in "
            f"obs/schema.py:KNOWN_KINDS"
        )
    for k, v in obj.items():
        if isinstance(v, _SCALAR):
            continue
        if isinstance(v, list) and all(isinstance(x, _SCALAR) for x in v):
            continue
        errs.append(
            f"key {k!r} holds a {type(v).__name__} — records must stay flat "
            f"(scalars or lists of scalars)"
        )
    return errs


def stamp(record: dict, kind: Optional[str] = None) -> dict:
    """Stamp ``record`` with the stream contract fields, in place.

    The one helper every JSONL emitter that builds records by hand should
    flow through (``scripts/ddlpc_check.py``'s jsonl-stamp rule looks for
    it): sets ``schema`` (and ``time``) if absent, and — when ``kind`` is
    given — a ``kind`` that must already be registered in
    :data:`KNOWN_KINDS`, so a typo'd emitter fails at the emit site
    instead of at lint time."""
    if kind is not None:
        if kind not in KNOWN_KINDS:
            raise ValueError(
                f"unregistered record kind {kind!r} — add it to "
                f"obs/schema.py:KNOWN_KINDS first"
            )
        record.setdefault("kind", kind)
    record.setdefault("schema", SCHEMA_VERSION)
    record.setdefault("time", time.time())
    return record


def is_stale(obj: object) -> bool:
    """True for a record stamped with an OLDER (still valid) schema
    version: conforming, but worth reporting — the stream predates the
    current tooling (e.g. a long-lived run tailed across an upgrade)."""
    if not isinstance(obj, dict):
        return False
    schema = obj.get("schema")
    return (
        isinstance(schema, int)
        and not isinstance(schema, bool)
        and 0 <= schema < SCHEMA_VERSION
    )
