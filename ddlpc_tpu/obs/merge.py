"""Stitch per-process span streams into ONE fleet timeline (ISSUE 14).

A fleet request's story is written by N+1 processes — the router's
``route_request``/``router_attempt`` spans land in
``<fleet_dir>/router_spans.jsonl`` and each replica's
``serve_request``/``batch_coalesce``/``jit_execute`` spans land in its own
``serve_spans.jsonl`` — all sharing one wall clock (the tracer's JSONL
``time`` field is epoch seconds) and one request identity (the 32-hex
``trace_id`` minted by the router and carried on the ``traceparent``
header; ``obs/tracing.py``).  This module joins them:

- :func:`read_spans` loads any number of span JSONL files;
- :func:`build_timeline` renders a Perfetto-loadable Chrome trace where
  every process is its own track (``pid`` metadata from ``service`` +
  recorded pid) and cross-process hops are FLOW arrows: a router attempt
  span carries its 16-hex ``span_hex``, the replica's ``serve_request``
  root records the same value as ``remote_parent``, and the matching
  ``s``/``f`` flow events draw the arrow from dispatch to execution — a
  hedged request shows the router attempt spans parented over BOTH
  replicas' slot work;
- :func:`attribution` reduces one request's merged spans to the
  end-to-end table (router wait / network hop / replica queue / assembly
  / device / stitch) that ``scripts/fleet_report.py`` renders.

Deliberately jax-free and numpy-free (stdlib only): merging is an
operator/CI activity that must run anywhere the streams can be copied.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ddlpc_tpu.utils.fsio import atomic_write_json

# Span names with a fixed role in the attribution table.
ROUTE_SPAN = "route_request"
ATTEMPT_SPAN = "router_attempt"
SERVE_SPAN = "serve_request"
# A request answered from the router's response cache: no attempt, no
# replica — the span IS the whole story (ISSUE 17: previously these
# traces dangled with no fleet-side record at all).
CACHE_SPAN = "cache_hit"


def read_spans(paths: Sequence[str]) -> List[dict]:
    """All ``kind="span"`` records from the given JSONL files, each
    annotated with its source file (``_src``, stripped before any
    re-emission).  Torn/corrupt lines are skipped — a live stream's last
    line may be mid-write."""
    out: List[dict] = []
    for path in paths:
        try:
            fh = open(path, "r", encoding="utf-8")
        except OSError:
            continue
        with fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("kind") == "span":
                    rec["_src"] = os.path.basename(path)
                    out.append(rec)
    out.sort(key=lambda r: r.get("time", 0.0))
    return out


def read_records(paths: Sequence[str]) -> List[dict]:
    """EVERY record from the given JSONL files — spans, metrics, fleet
    events, lineage, autoscale — annotated with ``_src`` and merged into
    one wall-clock order.  The lineage timeline needs the non-span
    streams too (``checkpoint_saved`` and ``fleet_serving`` are flat
    ``kind="lineage"`` records, reloads are ``kind="serve_reload"``), so
    this is :func:`read_spans` without the kind filter."""
    out: List[dict] = []
    for path in paths:
        try:
            fh = open(path, "r", encoding="utf-8")
        except OSError:
            continue
        with fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    rec["_src"] = os.path.basename(path)
                    out.append(rec)
    out.sort(key=lambda r: r.get("time", 0.0))
    return out


def fleet_span_files(fleet_dir: str) -> List[str]:
    """The standard fleet layout: the router's stream plus one
    ``serve_spans.jsonl`` per replica home (``<fleet_dir>/r<idx>/``)."""
    paths = [os.path.join(fleet_dir, "router_spans.jsonl")]
    try:
        entries = sorted(os.listdir(fleet_dir))
    except OSError:
        entries = []
    for entry in entries:
        p = os.path.join(fleet_dir, entry, "serve_spans.jsonl")
        if os.path.isfile(p):
            paths.append(p)
    return [p for p in paths if os.path.isfile(p)]


def span_trace_ids(rec: dict) -> Set[str]:
    """Every request trace id a span belongs to: its own ``trace_id``
    plus the batcher's ``trace_ids`` list (a worker-thread batch span
    serves several requests at once)."""
    out: Set[str] = set()
    tid = rec.get("trace_id")
    if isinstance(tid, str):
        out.add(tid)
    tids = rec.get("trace_ids")
    if isinstance(tids, list):
        out.update(str(t) for t in tids)
    return out


def filter_trace(records: Iterable[dict], trace_id: str) -> List[dict]:
    return [r for r in records if trace_id in span_trace_ids(r)]


def trace_ids(records: Iterable[dict]) -> List[str]:
    """Request trace ids in first-seen order, roots (``route_request`` /
    ``serve_request`` / ``cache_hit``) first so callers can iterate real
    requests rather than every process's run id."""
    seen: List[str] = []
    for r in records:
        if r.get("name") not in (ROUTE_SPAN, SERVE_SPAN, CACHE_SPAN):
            continue
        t = r.get("trace_id")
        if isinstance(t, str) and t not in seen:
            seen.append(t)
    return seen


def filter_lineage(records: Iterable[dict], lineage_id: str) -> List[dict]:
    """Every record attributed to one checkpoint save — the trainer's
    ``checkpoint_saved`` event, serve-side reloads, the fleet's
    ``fleet_serving`` event, and any span stamped with the id."""
    return [r for r in records if r.get("lineage_id") == lineage_id]


def _process_key(rec: dict) -> Tuple[str, object]:
    # service + recorded OS pid identifies a process; streams predating
    # the pid field fall back to their source file.
    return (
        str(rec.get("service", "?")),
        rec.get("pid", rec.get("_src", "?")),
    )


def build_timeline(
    records: Sequence[dict], trace_id: Optional[str] = None
) -> dict:
    """A Chrome-trace document (``{"traceEvents": [...]}``) over the given
    span records — optionally filtered to one request's ``trace_id`` —
    with one track per source process and flow arrows across the
    router→replica hops.  Loadable directly in Perfetto."""
    if trace_id is not None:
        records = filter_trace(records, trace_id)
    records = sorted(records, key=lambda r: r.get("time", 0.0))
    if not records:
        return {"traceEvents": [], "metadata": {"spans": 0}}
    t0 = min(r.get("time", 0.0) for r in records)
    pids: Dict[Tuple[str, object], int] = {}
    meta: List[dict] = []
    events: List[dict] = []
    # remote_parent → the flow arrow's destination(s); span_hex → source.
    hop_sources: Dict[str, Tuple[int, int, float]] = {}
    hop_dests: List[Tuple[str, int, int, float]] = []
    for rec in records:
        key = _process_key(rec)
        pid = pids.get(key)
        if pid is None:
            pid = pids[key] = len(pids) + 1
            meta.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": f"{key[0]}/{key[1]}"},
                }
            )
        ts = (rec.get("time", t0) - t0) * 1e6
        dur = max(float(rec.get("dur_s", 0.0)) * 1e6, 1.0)
        tid = int(rec.get("tid", 0))
        args = {
            k: v
            for k, v in rec.items()
            if k
            not in (
                "schema", "kind", "time", "dur_s", "pid", "tid", "_src",
                "name",
            )
        }
        events.append(
            {
                "name": str(rec.get("name", "?")),
                "ph": "X",
                "ts": ts,
                "dur": dur,
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
        hexid = rec.get("span_hex")
        if isinstance(hexid, str):
            hop_sources[hexid] = (pid, tid, ts)
        rp = rec.get("remote_parent")
        if isinstance(rp, str):
            hop_dests.append((rp, pid, tid, ts))
    for rp, pid, tid, ts in hop_dests:
        src = hop_sources.get(rp)
        if src is None:
            continue  # the source stream wasn't part of this merge
        s_pid, s_tid, s_ts = src
        common = {"cat": "fleet", "name": "hop", "id": rp}
        events.append(
            {"ph": "s", "pid": s_pid, "tid": s_tid, "ts": s_ts, **common}
        )
        events.append(
            {
                "ph": "f", "bp": "e", "pid": pid, "tid": tid, "ts": ts,
                **common,
            }
        )
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "metadata": {
            "spans": len(records),
            "processes": len(pids),
            "trace_id": trace_id,
            "t0_epoch_s": round(t0, 6),
        },
    }


def write_trace(doc: dict, path: str) -> str:
    """Rename-atomic trace.json write (a merged trace is an artifact —
    readers must never see a torn one)."""
    atomic_write_json(path, doc)
    return path


# ---------------------------------------------------------------------------
# per-request attribution
# ---------------------------------------------------------------------------


def _sum_dur(records: Iterable[dict], name: str) -> float:
    return sum(
        float(r.get("dur_s", 0.0)) for r in records if r.get("name") == name
    )


def attribution(records: Sequence[dict], trace_id: str) -> Dict[str, object]:
    """One request's end-to-end phase table as a FLAT record
    (``kind="fleet_trace"`` once stamped): where its wall time went —

    - ``router_wait_s``   — request arrival → first attempt dispatched
      (admission waits, zero-eligible blips, shed checks);
    - ``network_hop_s``   — winning attempt duration minus the replica's
      serve_request duration (HTTP + queue on both sides of the socket);
    - ``replica_queue_s`` — batcher admission → batch take
      (``batch_coalesce``) for batches serving this request;
    - ``assembly_s``      — window planning + enqueue on the replica;
    - ``device_s``        — ``jit_execute`` for those batches;
    - ``stitch_s``        — logits → class-map assembly.

    Batch spans serve several requests at once, so replica_queue/device
    are ATTRIBUTED, not exclusive — the table explains a latency, it does
    not bill exclusive device time."""
    recs = filter_trace(records, trace_id)
    route = next(
        (r for r in recs if r.get("name") == ROUTE_SPAN), None
    )
    if route is None:
        # Answered from the response cache: the cache_hit span is the
        # whole request — same attributable identity (model step +
        # lineage id), zero replica phases.
        hit = next((r for r in recs if r.get("name") == CACHE_SPAN), None)
        if hit is not None:
            return {
                "kind": "fleet_trace",
                "trace_id": trace_id,
                "cache_hit": True,
                "total_s": round(float(hit.get("dur_s", 0.0)), 6),
                "status": hit.get("status"),
                "model_step": hit.get("model_step"),
                "lineage_id": hit.get("lineage_id"),
                "attempts": 0,
                "retries": 0,
                "hedges": 0,
                "processes": len({_process_key(r)[1] for r in recs}),
                "spans": len(recs),
            }
    attempts = sorted(
        (r for r in recs if r.get("name") == ATTEMPT_SPAN),
        key=lambda r: r.get("time", 0.0),
    )
    serves = {
        r.get("remote_parent"): r
        for r in recs
        if r.get("name") == SERVE_SPAN and r.get("remote_parent")
    }
    out: Dict[str, object] = {
        "kind": "fleet_trace",
        "trace_id": trace_id,
        "attempts": len(attempts),
        "retries": sum(1 for a in attempts if a.get("reason") == "retry"),
        "hedges": sum(1 for a in attempts if a.get("reason") == "hedge"),
        "processes": len({_process_key(r)[1] for r in recs}),
        "spans": len(recs),
    }
    out["cache_hit"] = False
    if route is not None:
        out["total_s"] = round(float(route.get("dur_s", 0.0)), 6)
        out["status"] = route.get("status")
        if route.get("model_step") is not None:
            out["model_step"] = route.get("model_step")
        if route.get("lineage_id") is not None:
            out["lineage_id"] = route.get("lineage_id")
        if attempts:
            out["router_wait_s"] = round(
                max(attempts[0].get("time", 0.0) - route.get("time", 0.0),
                    0.0),
                6,
            )
    # The winning attempt: answered (status < 500) and not cancelled;
    # hedge losers stay in the count above but don't define the hop.
    winner = next(
        (
            a
            for a in attempts
            if isinstance(a.get("status"), int)
            and a["status"] < 500
            and not a.get("cancelled")
        ),
        None,
    )
    if winner is not None:
        out["winner_replica"] = winner.get("replica")
        out["winner_reason"] = winner.get("reason")
        serve = serves.get(winner.get("span_hex"))
        if serve is not None:
            out["network_hop_s"] = round(
                max(
                    float(winner.get("dur_s", 0.0))
                    - float(serve.get("dur_s", 0.0)),
                    0.0,
                ),
                6,
            )
    out["replica_queue_s"] = round(_sum_dur(recs, "batch_coalesce"), 6)
    out["assembly_s"] = round(
        _sum_dur(recs, "window_plan") + _sum_dur(recs, "enqueue"), 6
    )
    out["device_s"] = round(_sum_dur(recs, "jit_execute"), 6)
    out["stitch_s"] = round(_sum_dur(recs, "stitch"), 6)
    return out


def summarize_requests(records: Sequence[dict]) -> List[Dict[str, object]]:
    """Attribution rows for every request trace present in ``records``
    (only traces with a router ``route_request`` root — a replica's
    local-only traces are not fleet requests)."""
    routed = {
        r.get("trace_id")
        for r in records
        if r.get("name") in (ROUTE_SPAN, CACHE_SPAN)
        and isinstance(r.get("trace_id"), str)
    }
    return [
        attribution(records, t)
        for t in trace_ids(records)
        if t in routed
    ]


# ---------------------------------------------------------------------------
# lineage timeline (ISSUE 17)
# ---------------------------------------------------------------------------

def lineage_timeline(
    records: Sequence[dict], lineage_id: str
) -> Dict[str, object]:
    """One checkpoint's life on the merged timeline: trainer save →
    per-replica reloads → whole-fleet serving → the requests it
    answered.  Works over :func:`read_records` output (mixed streams);
    derives ``deploy_latency_s`` = fleet_serving time − ``saved_at``
    when both ends are present."""
    recs = filter_lineage(records, lineage_id)
    events: List[dict] = []
    saved_at: Optional[float] = None
    fleet_at: Optional[float] = None
    served = 0
    for r in recs:
        kind, name = r.get("kind"), r.get("name")
        event = r.get("event")
        if kind == "lineage" and event == "checkpoint_saved":
            sv = r.get("lineage_saved_at")
            if isinstance(sv, (int, float)) and not isinstance(sv, bool):
                saved_at = float(sv)
        if kind == "lineage" and event == "fleet_serving":
            fleet_at = float(r.get("time", 0.0)) or fleet_at
        if kind == "span" and name in (
            ROUTE_SPAN, SERVE_SPAN, CACHE_SPAN
        ):
            served += 1
        events.append(
            {
                "time": r.get("time"),
                "kind": kind,
                "event": event or name,
                "src": r.get("_src"),
                "step": r.get("step", r.get("lineage_step")),
            }
        )
    out: Dict[str, object] = {
        "lineage_id": lineage_id,
        "events": events,
        "records": len(recs),
        "requests_served": served,
        "saved_at": saved_at,
        "fleet_serving_at": fleet_at,
    }
    if saved_at is not None and fleet_at is not None:
        out["deploy_latency_s"] = round(max(fleet_at - saved_at, 0.0), 6)
    return out
