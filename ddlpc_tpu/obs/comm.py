"""Communication accounting: exact logical wire bytes + a fenced comm probe.

The ROADMAP's biggest open perf item — fused quantized collectives with
comm/compute overlap — cannot be judged without first knowing (a) how many
bytes each codec actually puts on the wire per step and (b) what fraction
of the step is communication.  This module owns both:

- **Byte accounting** (:func:`comm_plan`, :class:`CommAccountant`) —
  closed-form per-step logical payload bytes for every collective the
  step variants issue (``parallel/grad_sync.py``'s ``sync_gradients`` /
  ``sync_gradients_scatter``, ``parallel/compressed_allreduce.py``'s
  ring), pre-codec, post-codec and on-the-wire, published as
  ``ddlpc_comm_bytes_total{collective,codec,stage}`` counters and a
  ``ddlpc_comm_compression_ratio`` gauge.  "Logical" means the tensor
  bytes a replica contributes to the collective — what a compressed wire
  format carries; after the fused rewrite the simulate transport's
  collective operand really IS that narrow dtype wherever the lattice
  sums fit it exactly (``grad_sync.simulate_wire_dtype`` — the ``wire``
  stage rows), fp32 only on the fallback paths; the ring transport's
  numbers are its REAL per-hop wire bytes (``ring_wire_report``).
  Exactness is the contract: int8 → ``n·1 + 4`` (one global fp32 scale),
  float16 → ``n·2 + 4``, none → ``n·4`` (test-pinned against closed
  form).  A singleton data axis has no communication and counts zero.

- **Fenced comm-time probe** (:func:`make_comm_probe`) — a compiled
  program running ONLY the gradient sync (the training step's exact
  ``sync_gradients``/``sync_gradients_scatter`` call, codec fences and
  all) on a parameter-shaped dummy tree.  The trainer samples it on the
  existing ``trace_sync_every_steps`` cadence; the measured seconds yield
  ``ddlpc_comm_fraction`` (comm seconds / step seconds) and
  ``ddlpc_comm_overlap_headroom_s`` — the step-time saving a perfect
  backward/sync overlap could claim, ``min(t_comm, t_step − t_comm)`` —
  which is the committed baseline the future overlap PR is judged
  against (docs/PERF.md "Accounting").

jax stays a lazy import (probe construction only); the byte math is pure.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

# Wire itemsize per codec mode for the simulate transport's logical
# payload (the ring transport computes its own hop dtype — see
# compressed_allreduce.wire_dtype).
CODEC_ITEMSIZE = {"none": 4, "int8": 1, "float16": 2}
# One global (whole-model) fp32 absmax scale per quantized payload
# (ops/quantize.py:Encoded).
SCALE_BYTES = 4


def tree_elements(tree) -> int:
    """Total element count of a pytree of arrays/ShapeDtypeStructs."""
    import jax
    import numpy as np

    return int(
        sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))
    )


def codec_payload_bytes(n_elements: int, mode: str, n_scales: int = 1) -> int:
    """Logical payload bytes for ``n_elements`` after the codec: the wire
    dtype's bytes plus the global scale scalar(s) (quantizing modes only —
    bucketed syncs carry one fp32 scale per bucket)."""
    if mode not in CODEC_ITEMSIZE:
        raise ValueError(f"unknown compression mode {mode!r}")
    nbytes = n_elements * CODEC_ITEMSIZE[mode]
    if mode != "none":
        nbytes += SCALE_BYTES * n_scales
    return nbytes


def simulate_wire_row(compression, axis_size: int):
    """(hlo_dtype_name, itemsize) of the simulate transport's grad
    collective operand — the ACTUAL dtype on the wire after the fused
    rewrite (grad_sync.simulate_wire_dtype), distinct from the codec's
    declared loss model: 's8'/'s16'/'f16' when the lattice sums fit the
    narrow dtype, 'f32' otherwise (mode='none', quantize_local=False, or
    an axis too large for exact narrow sums)."""
    from ddlpc_tpu.parallel.grad_sync import simulate_wire_dtype

    wire = simulate_wire_dtype(axis_size, compression)
    if wire is None:
        return "f32", 4
    import numpy as np

    dt = np.dtype(wire)
    name = {"int8": "s8", "int16": "s16", "float16": "f16"}[dt.name]
    return name, dt.itemsize


def comm_plan(
    n_grad_elements: int,
    n_param_elements: int,
    compression,
    axis_size: int,
    variant: str,
    n_buckets: int = 1,
) -> List[Dict[str, object]]:
    """Per-optimizer-step collective rows for one step variant.

    ``variant`` ∈ ``allreduce`` (replicated shard_map step),
    ``zero1`` (full-mean all-reduce + params all-gather publish: 3·P),
    ``scatter`` (ZeRO-2: reduce-scatter grads + all-gather params tail
    publish: 2·P — the level that stops all-gathering what it just
    scattered), ``zero3`` (reduce-scatter grads + the gather-on-demand
    params all-gather at step HEAD: same 2·P volume as scatter, the
    all-gather just moved from tail publish to forward prologue),
    ``ring`` (compressed ppermute transport), ``gspmd`` (partitioner-
    inserted all-reduce; no per-replica quantize stage exists there, so
    the wire payload is fp32 — train_step.py documents why).

    Each row: ``collective``, ``codec`` (the mode the wire payload is in),
    ``bytes_pre`` (fp32 bytes entering the codec), ``bytes_post`` (the
    DECLARED loss-model payload leaving it — the historical convention,
    kept stable so old streams stay comparable), plus ``wire_dtype`` and
    ``bytes_wire`` — the ACTUAL HLO collective operand bytes after the
    fused rewrite: the narrow lattice payload plus one fp32 scale pmax
    per bucket where the fused path engages, fp32 otherwise (chunk
    padding depends on leaf shapes and is accounted exactly by the
    program auditor, not here).  ``n_buckets`` is the bucket count of
    ``CompressionConfig.bucket_mb`` (grad_sync.grad_bucket_groups): each
    bucket carries its own scale.  Singleton meshes communicate nothing
    → empty plan.
    """
    if axis_size <= 1:
        return []
    mode = compression.mode
    fp32 = n_grad_elements * 4
    if variant == "allreduce":
        # quantize_local is the codec stage ahead of the wire; without it
        # (or with mode none) the payload stays fp32.
        wire_mode = mode if (mode != "none" and compression.quantize_local) else "none"
        wire_name, wire_item = simulate_wire_row(compression, axis_size)
        scale_bytes = 0 if wire_name == "f32" else SCALE_BYTES * n_buckets
        return [
            {
                "collective": "all_reduce",
                "codec": wire_mode,
                "bytes_pre": fp32,
                "bytes_post": codec_payload_bytes(
                    n_grad_elements, wire_mode, n_buckets
                ),
                "wire_dtype": wire_name,
                "bytes_wire": n_grad_elements * wire_item + scale_bytes,
            }
        ]
    if variant == "zero1":
        # Full-mean all-reduce (the codec wire, same as 'allreduce') plus
        # the chunked update's fresh-params all-gather publish: 3·P.
        wire_mode = mode if (mode != "none" and compression.quantize_local) else "none"
        wire_name, wire_item = simulate_wire_row(compression, axis_size)
        scale_bytes = 0 if wire_name == "f32" else SCALE_BYTES * n_buckets
        return [
            {
                "collective": "all_reduce",
                "codec": wire_mode,
                "bytes_pre": fp32,
                "bytes_post": codec_payload_bytes(
                    n_grad_elements, wire_mode, n_buckets
                ),
                "wire_dtype": wire_name,
                "bytes_wire": n_grad_elements * wire_item + scale_bytes,
            },
            {
                "collective": "all_gather",
                "codec": "none",
                "bytes_pre": n_param_elements * 4,
                "bytes_post": n_param_elements * 4,
                "wire_dtype": "f32",
                "bytes_wire": n_param_elements * 4,
            },
        ]
    if variant in ("scatter", "zero3", "zero3_update"):
        wire_mode = mode if (mode != "none" and compression.quantize_local) else "none"
        wire_name, wire_item = simulate_wire_row(compression, axis_size)
        scale_bytes = 0 if wire_name == "f32" else SCALE_BYTES * n_buckets
        rows = [
            {
                "collective": "reduce_scatter",
                "codec": wire_mode,
                "bytes_pre": fp32,
                "bytes_post": codec_payload_bytes(
                    n_grad_elements, wire_mode, n_buckets
                ),
                "wire_dtype": wire_name,
                "bytes_wire": n_grad_elements * wire_item + scale_bytes,
            },
            # ZeRO-2: the fresh-params tail publish.  ZeRO-3: the
            # gather-on-demand at step head (params persist chunked, the
            # forward gathers them per leaf).  Same volume either way —
            # uncompressed by construction (params, not grads).
            {
                "collective": "all_gather",
                "codec": "none",
                "bytes_pre": n_param_elements * 4,
                "bytes_post": n_param_elements * 4,
                "wire_dtype": "f32",
                "bytes_wire": n_param_elements * 4,
            },
        ]
        # 'zero3_update' is the auditor's update-program slice of zero3:
        # the step-head params gather belongs to the TRAIN program, so
        # the bare update moves only the reduce-scatter.
        return rows[:1] if variant == "zero3_update" else rows
    if variant == "ring":
        if mode == "none":
            # The ring falls back to an exact pmean for mode='none'.
            return [
                {
                    "collective": "ring_all_reduce",
                    "codec": "none",
                    "bytes_pre": fp32,
                    "bytes_post": fp32,
                    "wire_dtype": "f32",
                    "bytes_wire": fp32,
                }
            ]
        import numpy as np

        from ddlpc_tpu.parallel.compressed_allreduce import (
            ring_wire_report,
            wire_dtype as ring_wire_dtype,
        )

        rep = ring_wire_report(n_grad_elements, axis_size, compression)
        levels = (
            compression.int8_levels if mode == "int8" else compression.fp16_levels
        )
        ring_name = {"int8": "s8", "int16": "s16"}[
            np.dtype(ring_wire_dtype(axis_size, levels)).name
        ]
        return [
            {
                "collective": "ring_all_reduce",
                "codec": mode,
                # The ring's REAL per-replica hop bytes, fp32 ring vs
                # quantized ring — exact by construction (dtype × chunk ×
                # hops), not the logical-payload convention above.
                "bytes_pre": rep["fp32_bytes_per_replica"],
                "bytes_post": rep["wire_bytes_per_replica"],
                # The ring always had the quantized dtype on the wire.
                "wire_dtype": ring_name,
                "bytes_wire": rep["wire_bytes_per_replica"],
            }
        ]
    if variant == "gspmd":
        return [
            {
                "collective": "all_reduce",
                "codec": "none",
                "bytes_pre": fp32,
                "bytes_post": fp32,
                "wire_dtype": "f32",
                "bytes_wire": fp32,
            }
        ]
    raise ValueError(f"unknown comm plan variant {variant!r}")


class CommAccountant:
    """Registry-backed per-step communication accounting.

    ``on_step`` (called once per optimizer step from the trainer loop —
    a handful of counter increments) accumulates the plan's byte rows
    into ``ddlpc_comm_bytes_total``; ``record_probe`` stores a sampled
    fenced comm-time measurement; ``publish`` refreshes the derived
    gauges and returns the flat ``kind="comm"`` JSONL record.
    """

    def __init__(self, registry, plan: List[Dict[str, object]], variant: str):
        self.plan = list(plan)
        self.variant = variant
        self._lock = threading.Lock()
        self._steps = 0
        self._probe_s: Optional[float] = None
        self._bytes = registry.counter(
            "ddlpc_comm_bytes_total",
            "Logical collective payload bytes per replica (pre_codec = "
            "fp32 entering the codec, post_codec = the DECLARED loss-"
            "model payload leaving it, wire = actual HLO collective "
            "operand bytes — narrow lattice dtype where the fused path "
            "engages; ring rows are real per-hop wire bytes).",
            labelnames=("collective", "codec", "stage"),
        )
        self._ratio = registry.gauge(
            "ddlpc_comm_compression_ratio",
            "Measured pre/post codec byte ratio per collective.",
            labelnames=("collective",),
        )
        self._g_comm_s = registry.gauge(
            "ddlpc_comm_seconds_per_step",
            "Sampled fenced gradient-sync seconds (comm-only program).",
        )
        self._g_frac = registry.gauge(
            "ddlpc_comm_fraction",
            "Sampled comm seconds over mean optimizer-step seconds.",
        )
        self._g_headroom = registry.gauge(
            "ddlpc_comm_overlap_headroom_s",
            "Per-step seconds a perfect comm/compute overlap could save: "
            "min(t_comm, t_step - t_comm).",
        )
        for row in self.plan:
            self._ratio.set(
                row["bytes_pre"] / max(row["bytes_post"], 1),
                collective=row["collective"],
            )

    def on_step(self, n: int = 1) -> None:
        for row in self.plan:
            self._bytes.inc(
                row["bytes_pre"] * n,
                collective=row["collective"],
                codec=row["codec"],
                stage="pre_codec",
            )
            self._bytes.inc(
                row["bytes_post"] * n,
                collective=row["collective"],
                codec=row["codec"],
                stage="post_codec",
            )
            self._bytes.inc(
                row["bytes_wire"] * n,
                collective=row["collective"],
                codec=row["codec"],
                stage="wire",
            )
        with self._lock:
            self._steps += n

    def record_probe(self, comm_seconds: float) -> None:
        with self._lock:
            self._probe_s = float(comm_seconds)
        self._g_comm_s.set(float(comm_seconds))

    def publish(self, step_time_s: Optional[float] = None) -> Dict[str, object]:
        with self._lock:
            steps = self._steps
            probe_s = self._probe_s
        rec: Dict[str, object] = {"kind": "comm", "variant": self.variant,
                                  "steps": steps}
        for row in self.plan:
            name = str(row["collective"])
            rec[f"{name}_bytes_pre_per_step"] = row["bytes_pre"]
            rec[f"{name}_bytes_post_per_step"] = row["bytes_post"]
            rec[f"{name}_codec"] = row["codec"]
            rec[f"{name}_wire_dtype"] = row["wire_dtype"]
            rec[f"{name}_bytes_wire_per_step"] = row["bytes_wire"]
            rec[f"{name}_compression_ratio"] = round(
                row["bytes_pre"] / max(row["bytes_post"], 1), 4
            )
        if probe_s is not None:
            rec["comm_s_per_step"] = round(probe_s, 6)
            if step_time_s and step_time_s > 0:
                frac = min(probe_s / step_time_s, 1.0)
                headroom = max(min(probe_s, step_time_s - probe_s), 0.0)
                self._g_frac.set(frac)
                self._g_headroom.set(headroom)
                rec["comm_fraction"] = round(frac, 4)
                rec["overlap_headroom_s"] = round(headroom, 6)
                rec["step_time_s"] = round(float(step_time_s), 6)
        return rec


def make_comm_probe(
    mesh,
    compression,
    params,
    data_axis: str = "data",
    scatter: bool = False,
    seed: int = 0,
):
    """A callable measuring the fenced gradient-sync seconds in isolation.

    Compiles the training step's EXACT sync call (``sync_gradients`` or,
    under the ZeRO-1 layout, ``sync_gradients_scatter`` — codec fences
    included) over a parameter-shaped dummy gradient tree, replicated the
    way the step sees it.  The first call warms up (compile + one run);
    every call returns the wall seconds of one synchronized execution.
    Runs nothing at construction time.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddlpc_tpu.parallel.grad_sync import (
        sync_gradients,
        sync_gradients_scatter,
    )
    from ddlpc_tpu.utils.compat import shard_map

    axis_size = mesh.shape[data_axis]
    use_scatter = bool(scatter) and axis_size > 1
    stochastic = (
        compression.mode != "none" and compression.rounding == "stochastic"
    )

    def body(grads):
        # Static-seed key built inside the program, the _rounding_rng
        # pattern (train_step.py): every probe run rounds with the same
        # noise — right for timing the codec's real threefry cost.
        key = jax.random.key(seed) if stochastic else None
        if use_scatter:
            return sync_gradients_scatter(
                grads, data_axis, compression, axis_size=axis_size, key=key
            )
        return sync_gradients(
            grads, data_axis, compression, axis_size=axis_size, key=key
        )

    out_spec = P(data_axis) if use_scatter else P()
    fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(),),
            out_specs=out_spec,
            check=False,
        )
    )

    state = {"warmed": False}

    def probe() -> float:
        # The dummy gradient tree is rebuilt per probe and dropped right
        # after: holding it between once-per-epoch samples would pin a
        # full grads-sized fp32 buffer per device for the whole run —
        # exactly the HBM the accounting exists to watch.  The jit cache
        # keeps the compile across probes (shapes are stable).
        rng = np.random.default_rng(0)
        grads = jax.tree.map(
            lambda p: jax.device_put(
                rng.standard_normal(p.shape).astype(np.float32) * 1e-3,
                NamedSharding(mesh, P()),
            ),
            params,
        )
        if not state["warmed"]:
            jax.block_until_ready(fn(grads))  # compile + warm
            state["warmed"] = True
        t0 = time.perf_counter()
        jax.block_until_ready(fn(grads))
        return time.perf_counter() - t0

    return probe
