"""Per-step FLOP model + live MFU / goodput accounting.

Two halves, one file, because they answer the same operating question —
"how far is this run from the hardware roof, and where did the wall-clock
go?" (the pod-scale JAX training playbook treats MFU/goodput as THE
operating metric, arxiv 2204.06514):

- **FLOP model** — the jaxpr conv-walk hoisted out of
  ``scripts/roofline.py`` (the ``obs/xplane.py`` precedent: one
  implementation for the CLI and the live hooks).  It traces the real
  per-micro-batch ``value_and_grad`` program — forward convs AND the two
  backward convs XLA derives per layer — so the per-step FLOP count is
  computed from the program that runs, not an architecture diagram.
  Computed ONCE at trainer start (tracing only, no compile/execute).

- **Accounting** — :class:`PerfAccountant` turns that model plus the
  trainer's stage timings into live gauges on the training ``/metrics``
  endpoint:

  * ``ddlpc_mfu`` — model FLOP utilization of the last epoch's mean step:
    ``flops_per_step / (step_time · peak_flops_per_device)``;
  * ``ddlpc_goodput`` — productive-step seconds over wall seconds since
    fit start, debiting checkpoint stalls, eval, data waits, and restart
    gaps (the downtime between a previous attempt's last breadcrumb and
    this process taking over — read from the resilience breadcrumb /
    ``resilience.jsonl``, docs/RESILIENCE.md);
  * ``ddlpc_goodput_debit_seconds_total{category}`` — where the
    non-productive wall went.

  Per-epoch summaries are also logged as flat ``kind="perf"`` JSONL
  records, which ``scripts/perf_report.py`` renders as the step-time
  attribution table.

Debits are measured on the training thread as disjoint intervals, so the
reconciliation invariant holds by construction (test-pinned):
``productive + Σ debits ≤ wall``.

jax is imported lazily (inside the functions that trace) so this module
stays importable from stdlib-only contexts, like the rest of ``obs/``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

# TPU v5e (v5 lite) peak dense bf16 FLOP/s per chip — the roofline
# denominator used across the repo (bench.py, docs/PERF.md).  Used as the
# ASSUMED peak whenever the backend's device kind is not in the table
# (e.g. the CPU test meshes) so MFU numbers stay comparable with the
# committed bench tables; ``ddlpc_peak_flops_assumed`` says so.
V5E_PEAK_FLOPS = 197e12

# Known accelerator peaks (dense bf16 FLOP/s per chip), keyed by substrings
# of ``jax.Device.device_kind``.  Deliberately short: entries are added
# when a backend is actually measured against (docs/PERF.md discipline).
_PEAK_BY_DEVICE_KIND = (
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
)


# --------------------------------------------------------------------------
# FLOP model: collect conv ops from the executed program
# --------------------------------------------------------------------------


def _sub_jaxprs(params):
    import jax
    import jax.extend.core  # noqa: F401  (binds jax.extend — plain `import jax` does not)

    for v in params.values():
        if isinstance(v, jax.extend.core.ClosedJaxpr):
            yield v.jaxpr
        elif hasattr(v, "eqns"):  # raw Jaxpr
            yield v
        elif isinstance(v, (list, tuple)):
            for q in v:
                if isinstance(q, jax.extend.core.ClosedJaxpr):
                    yield q.jaxpr
                elif hasattr(q, "eqns"):
                    yield q


def iter_eqns(jaxpr):
    """Every equation in a jaxpr, recursing into sub-jaxprs (scan/remat/...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        yield from (e for sub in _sub_jaxprs(eqn.params) for e in iter_eqns(sub))


def conv_flops(eqn) -> int:
    """2 * output_elements * KH * KW * Cin_per_group (MACs x 2)."""
    import numpy as np

    out = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    dn = eqn.params["dimension_numbers"]
    cin_per_group = rhs[dn.rhs_spec[1]]
    k_spatial = int(np.prod([rhs[d] for d in dn.rhs_spec[2:]]))
    return 2 * int(np.prod(out)) * k_spatial * cin_per_group


def collect_convs(cfg, micro_batch: int, channels: int = 3) -> Dict[tuple, dict]:
    """Unique conv signatures (with counts) in one micro-batch fwd+bwd.

    Traces the model's per-micro-batch ``value_and_grad`` jaxpr for
    ``cfg`` (an ``ExperimentConfig``) and collects every
    ``conv_general_dilated`` — this is the program that runs.  Returns
    ``{signature_key: {"eqn", "count", "flops"}}`` (the roofline CLI also
    needs the eqn to rebuild and time each signature).

    ``channels`` is the dataset's input channel count (the Trainer passes
    ``train_ds.image_shape[-1]``; the first conv's FLOPs depend on it).

    FLOPs caveat (same convention as the roofline): lhs-dilated
    (transposed/backward) convs are counted at their algorithmic cost
    including inserted zeros.
    """
    import jax
    import jax.numpy as jnp

    from ddlpc_tpu.models import build_model
    from ddlpc_tpu.ops.losses import softmax_cross_entropy

    # No norm_axis_name: sync-BN's pmean needs a mesh axis and does not
    # change any conv shape — this traces the per-device program.
    model = build_model(cfg.model)
    h, w = cfg.data.image_size
    # Everything abstract: params/stats from eval_shape, inputs as
    # ShapeDtypeStructs passed as ARGUMENTS (closed-over concrete zeros
    # would embed a micro_batch×H×W constant in the jaxpr — ~400 MB at the
    # flagship operating point).  Tracing allocates nothing.
    x_s = jax.ShapeDtypeStruct((micro_batch, h, w, channels), jnp.float32)
    y_s = jax.ShapeDtypeStruct((micro_batch, h, w), jnp.int32)
    variables = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((1, h, w, channels), jnp.float32),
            train=False,
        )
    )

    def loss_fn(params, stats, x, y):
        logits, _ = model.apply(
            {"params": params, "batch_stats": stats},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        return softmax_cross_entropy(logits, y, ignore_index=-1)

    jaxpr = jax.make_jaxpr(jax.value_and_grad(loss_fn))(
        variables["params"], variables.get("batch_stats", {}), x_s, y_s
    )
    convs: Dict[tuple, dict] = {}
    for eqn in iter_eqns(jaxpr.jaxpr):
        if eqn.primitive.name != "conv_general_dilated":
            continue
        lhs, rhs = (v.aval for v in eqn.invars[:2])
        dn = eqn.params["dimension_numbers"]
        key = (
            tuple(lhs.shape),
            str(lhs.dtype),
            tuple(rhs.shape),
            str(rhs.dtype),
            tuple(eqn.params["window_strides"]),
            tuple(eqn.params["lhs_dilation"]),
            tuple(eqn.params["rhs_dilation"]),
            tuple(map(tuple, eqn.params["padding"])),
            eqn.params["feature_group_count"],
            # The actual layout specs: fwd convs are NHWC/HWIO but the
            # weight-gradient convs XLA derives contract over batch with
            # transposed specs — reconstruction from a fixed layout string
            # would measure a different program.
            (tuple(dn.lhs_spec), tuple(dn.rhs_spec), tuple(dn.out_spec)),
        )
        if key not in convs:
            convs[key] = dict(eqn=eqn, count=0, flops=conv_flops(eqn))
        convs[key]["count"] += 1
    return convs


_STEP_FLOPS_CACHE: Dict[tuple, int] = {}


def conv_step_flops(
    cfg, micro_batch: int, sync_period: int, channels: int = 3
) -> int:
    """Conv FLOPs of one OPTIMIZER step per device: ``sync_period``
    micro-batches of forward+backward at the per-replica ``micro_batch``.
    Non-conv FLOPs (norms, loss, Adam) are deliberately excluded — convs
    are >99% of this zoo's step and the roofline uses the same convention,
    so MFU here composes with the committed per-shape ceiling tables.
    Memoized per (model config, image size, micro_batch, channels): the
    trace costs ~0.5 s warm, and test suites construct many same-config
    Trainers."""
    key = (cfg.model, tuple(cfg.data.image_size), int(micro_batch),
           int(channels))
    per_micro = _STEP_FLOPS_CACHE.get(key)
    if per_micro is None:
        convs = collect_convs(cfg, micro_batch, channels=channels)
        per_micro = sum(c["count"] * c["flops"] for c in convs.values())
        _STEP_FLOPS_CACHE[key] = per_micro
    return sync_period * per_micro


def resolve_peak_flops(configured: float = 0.0) -> Tuple[float, bool]:
    """(peak FLOP/s per device, assumed?) for the MFU denominator.

    ``configured`` > 0 wins (``TrainConfig.peak_flops_per_device``).
    Otherwise the backend's device kind is looked up; unknown kinds (CPU
    test meshes, new accelerators) fall back to the v5e peak with
    ``assumed=True`` so the gauge stays comparable with the committed
    bench tables rather than fabricating a per-host number."""
    if configured and configured > 0:
        return float(configured), False
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        kind = ""
    for sub, peak in _PEAK_BY_DEVICE_KIND:
        if sub in kind:
            return peak, False
    return V5E_PEAK_FLOPS, True


def restart_gap_seconds(workdir: str, now: Optional[float] = None) -> float:
    """Downtime this attempt inherits from a previous one, in seconds.

    A supervised restart (docs/RESILIENCE.md) leaves two timestamps a new
    process can read before it overwrites them: the previous attempt's
    last ``breadcrumb.json`` (rewritten at every phase transition) and the
    supervisor's ``resilience.jsonl`` records.  The gap — newest such
    timestamp to now — is wall-clock during which no training happened and
    is debited from goodput as category ``restart``.

    The breadcrumb's phase GATES the whole computation: only a crumb from
    an INTERRUPTED run (phase other than ``done``) means this attempt is a
    restart.  A fresh workdir (no crumb) or a completed one (``done``) has
    no gap even when an old ``resilience.jsonl`` is still lying around —
    resuming a finished run days later is a new run, not downtime.
    Best-effort: accounting must never take down the run it describes."""
    now = time.time() if now is None else now
    try:
        from ddlpc_tpu.resilience.protocol import read_breadcrumb

        crumb = read_breadcrumb(workdir)
    except Exception:
        crumb = None
    if not crumb or crumb.get("phase") == "done":
        return 0.0
    latest = 0.0
    t = crumb.get("time")
    if isinstance(t, (int, float)):
        latest = float(t)
    try:
        import json
        import os

        path = os.path.join(workdir, "resilience.jsonl")
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    t = rec.get("time")
                    if isinstance(t, (int, float)):
                        latest = max(latest, float(t))
    except Exception:
        pass
    if latest <= 0.0:
        return 0.0
    return max(now - latest, 0.0)


# --------------------------------------------------------------------------
# Live accounting
# --------------------------------------------------------------------------


class PerfAccountant:
    """Live MFU + goodput gauges over a training run's wall clock.

    The trainer feeds it disjoint measured intervals from the training
    thread — ``productive`` (compiled step dispatch+sync seconds) and
    ``debit`` categories (data waits, eval, checkpoint stalls) — plus the
    one-time restart gap; ``publish`` computes the gauges and returns the
    flat ``kind="perf"`` record for the JSONL stream.  Thread-safe (the
    telemetry endpoint scrapes concurrently with the loop).
    """

    def __init__(
        self,
        registry,
        flops_per_step: int,
        peak_flops: float,
        peak_assumed: bool = False,
        restart_gap_s: float = 0.0,
        service: str = "train",
    ):
        self._lock = threading.Lock()
        self.flops_per_step = int(flops_per_step)
        self.peak_flops = float(peak_flops)
        self.peak_assumed = bool(peak_assumed)
        self.restart_gap_s = float(restart_gap_s)
        self._origin: Optional[float] = None
        self._productive_s = 0.0
        self._steps = 0
        self._debits: Dict[str, float] = {}
        if restart_gap_s > 0:
            self._debits["restart"] = float(restart_gap_s)
        self._g_mfu = registry.gauge(
            "ddlpc_mfu",
            "Model FLOP utilization of the last epoch's mean step "
            "(conv FLOPs / (step seconds * peak FLOP/s per device)).",
        )
        self._g_goodput = registry.gauge(
            "ddlpc_goodput",
            "Productive-step seconds over wall seconds since fit start, "
            "debiting data waits, eval, checkpoint stalls, restart gaps.",
        )
        self._g_flops = registry.gauge(
            "ddlpc_flops_per_step",
            "Per-device conv FLOPs of one optimizer step (traced jaxpr).",
        )
        self._g_peak = registry.gauge(
            "ddlpc_peak_flops_per_device",
            "Peak FLOP/s per device used as the MFU denominator.",
        )
        self._g_assumed = registry.gauge(
            "ddlpc_peak_flops_assumed",
            "1 when the peak is an assumption (unknown device kind, v5e "
            "peak used for comparability), 0 when known/configured.",
        )
        self._g_debit = registry.gauge(
            "ddlpc_goodput_debit_seconds_total",
            "Cumulative non-productive wall seconds, by category.",
            labelnames=("category",),
        )
        self._g_flops.set(float(self.flops_per_step))
        self._g_peak.set(self.peak_flops)
        self._g_assumed.set(1.0 if peak_assumed else 0.0)
        if restart_gap_s > 0:
            self._g_debit.set(restart_gap_s, category="restart")

    def start(self) -> None:
        """Mark fit start (wall origin).  Idempotent across epochs; a
        second fit() on the same trainer continues the same wall clock."""
        with self._lock:
            if self._origin is None:
                self._origin = time.monotonic()

    def productive(self, seconds: float, steps: int = 0) -> None:
        """Credit compiled-step seconds (the thing goodput counts)."""
        with self._lock:
            self._productive_s += max(float(seconds), 0.0)
            self._steps += int(steps)

    def debit(self, category: str, seconds: float) -> None:
        """Charge non-productive wall seconds to a category (data, eval,
        checkpoint, ...)."""
        seconds = max(float(seconds), 0.0)
        with self._lock:
            self._debits[category] = self._debits.get(category, 0.0) + seconds
        self._g_debit.set(self._debits[category], category=category)

    def mfu(self, step_time_s: float) -> float:
        """MFU of a step of ``step_time_s`` seconds under the model."""
        if step_time_s <= 0 or self.peak_flops <= 0:
            return 0.0
        return self.flops_per_step / (step_time_s * self.peak_flops)

    def publish(self, step_time_s: Optional[float] = None) -> Dict[str, object]:
        """Refresh the gauges; returns the flat ``kind="perf"`` record.

        ``step_time_s`` is the last epoch's mean optimizer-step seconds
        (the MFU numerator's denominator); omitted, the cumulative mean
        of credited productive seconds per step is used."""
        with self._lock:
            origin = self._origin
            productive = self._productive_s
            steps = self._steps
            debits = dict(self._debits)
        wall = (
            time.monotonic() - origin if origin is not None else 0.0
        ) + self.restart_gap_s
        if step_time_s is None and steps > 0:
            step_time_s = productive / steps
        mfu = self.mfu(step_time_s) if step_time_s else 0.0
        goodput = productive / wall if wall > 0 else 0.0
        self._g_mfu.set(mfu)
        self._g_goodput.set(goodput)
        rec: Dict[str, object] = {
            "kind": "perf",
            "mfu": round(mfu, 6),
            "goodput": round(goodput, 6),
            "flops_per_step": self.flops_per_step,
            "peak_flops_per_device": self.peak_flops,
            "peak_flops_assumed": self.peak_assumed,
            "productive_s": round(productive, 4),
            "wall_s": round(wall, 4),
            "steps": steps,
        }
        if step_time_s:
            rec["step_time_s"] = round(float(step_time_s), 6)
        attributed = productive
        for cat, secs in sorted(debits.items()):
            rec[f"debit_{cat}_s"] = round(secs, 4)
            attributed += secs
        # The residual the measured intervals do not cover (compile time,
        # logging, loop overhead...).  Negative only by clock skew.
        rec["other_s"] = round(max(wall - attributed, 0.0), 4)
        return rec
