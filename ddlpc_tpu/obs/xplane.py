"""Self-time aggregation for JAX xplane profiler traces.

The one implementation behind ``scripts/xplane_top.py`` (offline CLI),
``scripts/trace_step.py`` (head-bench tracing) and the on-demand profiling
hooks (obs/profiling.py: the Trainer's SIGUSR2 trigger and the serve
``/debug/trace`` endpoint) — moved here from the script so the CLI and the
live hooks can never drift.

Self time = event duration minus the time of nested children on the same
line, which is what the tensorboard-plugin-profile op profile would show —
that plugin's converter is incompatible with the TF pinned in this image,
so this parses the xplane proto directly.

Plane selection: TPU/GPU traces put compiled ops on ``/device:...`` planes
under an "XLA Ops" line (:func:`self_times`, the historical behavior).  CPU
traces have no device plane — the ops land on host-plane lines named
``tf_XLAEigen/...`` / ``tf_XLATfrtCpuClient/...`` — so
:func:`self_times_any` falls back to those, which is what makes the
on-demand round trip work on the CPU backend too.

The TF xplane proto import is optional at module level: importing this
module never fails, and every entry point raises :class:`XplaneUnavailable`
with an actionable message when the proto is missing (instead of the bare
ImportError traceback the old script produced).
"""

from __future__ import annotations

import collections
import glob
import os
from typing import Counter, Iterator, List, Tuple

XPLANE_IMPORT_HINT = (
    "the TF xplane proto (tensorflow.tsl.profiler.protobuf.xplane_pb2) is "
    "not importable in this environment, so profiler traces cannot be "
    "aggregated. The raw trace directory is still valid — view it with "
    "TensorBoard/xprof elsewhere, or install a TensorFlow (or tsl protobuf) "
    "build that provides the proto to aggregate here."
)


class XplaneUnavailable(RuntimeError):
    """The TF xplane proto import is missing — aggregation cannot run."""


def _load_pb2():
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception as e:  # ImportError or TF's own init failures
        raise XplaneUnavailable(f"{XPLANE_IMPORT_HINT} ({e!r})") from e
    return xplane_pb2


def have_xplane() -> bool:
    """Whether trace aggregation can run in this environment."""
    try:
        _load_pb2()
        return True
    except XplaneUnavailable:
        return False


def load_xspace(trace_dir: str):
    """Parse the newest ``.xplane.pb`` under a ``jax.profiler`` trace dir."""
    xplane_pb2 = _load_pb2()
    paths = sorted(glob.glob(f"{trace_dir}/plugins/profile/*/*.xplane.pb"))
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def _line_self_times(events, ev_meta) -> Tuple[Counter, Counter]:
    """(self-time ps, count) per op name for one line's event list.

    Sort children after their enclosing parent at equal offsets (longer
    event first), or same-start nesting inverts the parent/child stack and
    produces negative self-times.
    """
    evs = sorted(
        (e.offset_ps, -e.duration_ps, ev_meta.get(e.metadata_id, "?"))
        for e in events
    )
    evs = [(off, -negdur, name) for off, negdur, name in evs]
    agg: Counter = collections.Counter()
    cnt: Counter = collections.Counter()
    stack: list = []  # [start, end, name, child_time]

    def pop_until(t: float) -> None:
        while stack and stack[-1][1] <= t:
            s, e, n, ct = stack.pop()
            agg[n] += (e - s) - ct
            cnt[n] += 1
            if stack:
                stack[-1][3] += e - s

    for off, dur, name in evs:
        pop_until(off)
        stack.append([off, off + dur, name, 0])
    pop_until(float("inf"))
    return agg, cnt


def self_times(trace_dir: str) -> Iterator[Tuple[str, Counter, Counter]]:
    """(plane name, self-time ps by op, count by op) per ``/device:`` plane
    — the historical TPU/GPU contract (scripts/trace_step.py depends on
    exactly this: device planes only, "XLA Ops" line only)."""
    xs = load_xspace(trace_dir)
    for plane in xs.planes:
        if not plane.name.startswith("/device:"):
            continue
        ev_meta = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            agg, cnt = _line_self_times(line.events, ev_meta)
            yield plane.name, agg, cnt


def self_times_any(trace_dir: str) -> Iterator[Tuple[str, Counter, Counter]]:
    """Like :func:`self_times` but never empty-handed on a valid trace:
    when no ``/device:`` plane exists (CPU backend) it aggregates the host
    plane's XLA executor lines (``tf_XLA*``) instead, merged per plane —
    each line is one executor thread, so self time nests within a line."""
    xs = load_xspace(trace_dir)
    found_device = False
    for plane in xs.planes:
        if not plane.name.startswith("/device:"):
            continue
        ev_meta = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            found_device = True
            agg, cnt = _line_self_times(line.events, ev_meta)
            yield plane.name, agg, cnt
    if found_device:
        return
    for plane in xs.planes:
        if not plane.name.startswith("/host:"):
            continue
        ev_meta = {k: v.name for k, v in plane.event_metadata.items()}
        agg: Counter = collections.Counter()
        cnt: Counter = collections.Counter()
        hit = False
        for line in plane.lines:
            if not line.name.startswith("tf_XLA"):
                continue
            hit = True
            a, c = _line_self_times(line.events, ev_meta)
            agg.update(a)
            cnt.update(c)
        if hit:
            yield plane.name, agg, cnt


def top_ops_report(
    trace_dir: str, top: int = 30, steps: int = 1, tag: str = ""
) -> dict:
    """The committed top-ops JSON format (docs/head_bench/trace_*.json
    introduced it; the on-demand hooks emit the same shape, plus the planes
    the ops came from).  ``steps`` normalizes to per-step milliseconds."""
    steps = max(int(steps), 1)
    agg: Counter = collections.Counter()
    cnt: Counter = collections.Counter()
    planes: List[str] = []
    for plane_name, a, c in self_times_any(trace_dir):
        planes.append(plane_name)
        agg.update(a)
        cnt.update(c)
    total_ps = sum(agg.values())
    return {
        "tag": tag,
        "trace_dir": os.path.abspath(trace_dir),
        "planes": planes,
        "steps_traced": steps,
        "device_total_ms": round(total_ps / 1e9, 3),
        "per_step_ms": round(total_ps / 1e9 / steps, 3),
        "top_self_time": [
            {
                "op": name[:160],
                "self_ms_per_step": round(ps / 1e9 / steps, 4),
                "count": cnt[name],
            }
            for name, ps in agg.most_common(top)
        ],
    }
