"""Span-based tracing with JSONL and Chrome-trace-event exporters.

A :class:`Tracer` names a run (one trace id); a :class:`Span` names a timed
phase within it (data wait, step dispatch, loader gather, serve coalesce,
jit execute, ...).  Spans nest per thread — the parent id comes from a
thread-local stack — and cross-thread phases whose start and end are
observed on different threads (the serve batcher's enqueue→coalesce wait)
are recorded with the explicit :meth:`Tracer.add_span`.

Two exporters, both always on when the tracer is enabled:

- **JSONL**: one flat record per span appended to ``jsonl_path`` as the
  span closes — the same stream shape as ``metrics.jsonl`` (schema-stamped,
  one flat JSON object per line) so ``scripts/obs_tail.py`` tails spans and
  metrics with the same code;
- **Chrome trace events**: complete ("ph": "X") events buffered in memory
  and written by :meth:`flush`/:meth:`close` as a ``trace.json`` loadable
  directly in Perfetto / chrome://tracing.  Buffering is bounded at
  ``max_events``; overflow increments ``dropped_events`` instead of growing
  without bound on a week-long run (the JSONL stream is the durable
  record).

Overhead discipline (the tentpole bar: ~0 disabled, ≤2% of step time
enabled — measured numbers in docs/OBSERVABILITY.md):

- disabled, ``span()`` returns a shared no-op context manager after one
  attribute test — no allocation, no clock read, no lock;
- enabled, a span costs two ``perf_counter`` reads, one dict/list append
  under the lock, and one buffered file write.

Cross-process trace context (the fleet tentpole, ISSUE 14): a request's
identity is a W3C-style pair — a 32-hex ``trace_id`` plus a 16-hex span
id — carried between processes on the ``traceparent`` HTTP header
(``00-<trace_id>-<span_id>-01``).  :meth:`Tracer.bind` installs a
(trace_id, remote parent) pair on the CURRENT THREAD; every span recorded
under the binding stamps that ``trace_id`` into its JSONL record instead
of the tracer's own run id, and a binding's ROOT spans (no local parent)
additionally record ``remote_parent`` — the hex span id of the upstream
process's span — so ``obs/merge.py`` can stitch the per-process streams
into one fleet timeline.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
import uuid
from contextlib import contextmanager, nullcontext
from typing import Dict, List, Optional, Tuple

from ddlpc_tpu.analysis import lockcheck
from ddlpc_tpu.obs.schema import SCHEMA_VERSION

# -- cross-process trace context (W3C traceparent shape) ---------------------

TRACEPARENT_HEADER = "traceparent"

_NULL_BIND = nullcontext()


def new_trace_id() -> str:
    """32 lowercase hex chars — one per REQUEST, shared across processes."""
    return uuid.uuid4().hex


def new_span_hex() -> str:
    """16 lowercase hex chars — a globally-unique span id for spans that
    must be referenced from ANOTHER process (the router's attempt spans)."""
    return uuid.uuid4().hex[:16]


def format_traceparent(trace_id: str, span_hex: str) -> str:
    """``00-<trace_id>-<span_id>-01`` (version 00, sampled flag)."""
    return f"00-{trace_id}-{span_hex}-01"


_HEX_DIGITS = frozenset("0123456789abcdef")


def _is_hex(s: str, n: int) -> bool:
    # Explicit charset, not int(s, 16): the W3C shape is LOWERCASE hex,
    # and int() would wave through '+'/'_'-decorated strings.
    return len(s) == n and all(c in _HEX_DIGITS for c in s)


def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """(trace_id, parent span hex) from a ``traceparent`` header, or None
    for anything malformed — a bad header must degrade to a fresh local
    trace, never into a request error."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_hex, _ = parts
    if not _is_hex(trace_id, 32) or not _is_hex(span_hex, 16):
        return None
    if trace_id == "0" * 32 or span_hex == "0" * 16:
        return None
    return trace_id, span_hex


class _NullSpan:
    """Shared no-op stand-in returned by a disabled tracer.  A singleton:
    ``tracer.span(...)`` on a disabled tracer allocates nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self


NULL_SPAN = _NullSpan()


class Span:
    """One timed, named phase.  Use as a context manager; ``set(**attrs)``
    attaches attributes (flat scalars) any time before exit."""

    __slots__ = ("_tracer", "name", "attrs", "span_id", "parent_id", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = 0
        self.parent_id = 0
        self._t0 = 0.0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        tr = self._tracer
        stack = tr._stack()
        self.parent_id = stack[-1] if stack else 0
        self.span_id = tr._next_id()
        stack.append(self.span_id)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        stack = self._tracer._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self._tracer._record(
            self.name,
            self._t0,
            t1,
            self.span_id,
            self.parent_id,
            threading.get_ident(),
            self.attrs,
        )
        return False


@lockcheck.guarded
class Tracer:
    """Trace/span-id issuing clock + exporters; thread-safe throughout.

    ``enabled=False`` (the default) makes every public method a near-free
    no-op — construct one unconditionally and let config decide.
    """

    def __init__(
        self,
        enabled: bool = False,
        service: str = "train",
        jsonl_path: Optional[str] = None,
        chrome_path: Optional[str] = None,
        max_events: int = 200_000,
    ):
        self.enabled = bool(enabled)
        self.service = service
        self.jsonl_path = jsonl_path
        self.chrome_path = chrome_path
        self.dropped_events = 0  # guarded-by: _lock
        if not self.enabled:
            return
        self.trace_id = uuid.uuid4().hex[:16]
        self.max_events = int(max_events)
        self._lock = lockcheck.lock("Tracer._lock")
        self._id = 0  # guarded-by: _lock
        self._pid = os.getpid()
        self._tls = threading.local()
        self._events: list = []  # guarded-by: _lock
        self._thread_names: dict = {}  # guarded-by: _lock
        # perf_counter is the span clock (monotonic, ns resolution); the
        # wall-clock anchor converts span starts to epoch seconds for the
        # JSONL stream so spans and metrics sort on one time axis.
        self._t0 = time.perf_counter()
        self._epoch0 = time.time() - self._t0
        self._jsonl: Optional[io.TextIOBase] = None  # guarded-by: _lock
        self._jsonl_flushed = self._t0  # guarded-by: _lock
        if jsonl_path is not None:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._jsonl = open(jsonl_path, "a")

    # -- span API ----------------------------------------------------------

    def span(self, name: str, **attrs):
        """Context manager timing a phase on the current thread."""
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, attrs)

    def add_span(
        self, name: str, start: float, end: float, **attrs
    ) -> None:
        """Record a phase whose start was observed on another thread (times
        from :meth:`now`).  No implicit parent — cross-thread spans are
        roots on their recording thread."""
        if not self.enabled:
            return
        self._record(
            name, start, end, self._next_id(), 0, threading.get_ident(), attrs
        )

    def now(self) -> float:
        """The tracer's clock (pair with :meth:`add_span`)."""
        return time.perf_counter() if self.enabled else 0.0

    # -- cross-process trace context ---------------------------------------

    def bind(self, trace_id: Optional[str], parent_hex: Optional[str] = None):
        """Context manager installing a request's cross-process identity on
        the CURRENT THREAD: spans recorded inside stamp ``trace_id`` into
        their JSONL records, and root spans (no local parent) record
        ``remote_parent=parent_hex`` — how a replica's ``serve_request``
        points back at the router attempt that dispatched it.  No-op when
        disabled or ``trace_id`` is None (a request with no/invalid
        ``traceparent`` keeps the tracer's own run id)."""
        if not self.enabled or trace_id is None:
            return _NULL_BIND
        return self._bind_ctx(trace_id, parent_hex)

    @contextmanager
    def _bind_ctx(self, trace_id: str, parent_hex: Optional[str]):
        prev = getattr(self._tls, "ctx", None)
        self._tls.ctx = (trace_id, parent_hex)
        try:
            yield self
        finally:
            self._tls.ctx = prev

    def current_trace_id(self) -> Optional[str]:
        """The bound request trace id on this thread, or None.  The
        batchers capture it at submit so batch spans executed on a worker
        thread can name every request trace they served."""
        if not self.enabled:
            return None
        ctx = getattr(self._tls, "ctx", None)
        return ctx[0] if ctx is not None else None

    # -- internals ---------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _record(
        self,
        name: str,
        t0: float,
        t1: float,
        span_id: int,
        parent_id: int,
        tid: int,
        attrs: dict,
    ) -> None:
        flat = {}
        for k, v in attrs.items():
            if isinstance(v, (str, int, float, bool, type(None))):
                flat[k] = v
            elif isinstance(v, (list, tuple)) and all(
                isinstance(x, (str, int, float, bool, type(None))) for x in v
            ):
                # Lists of scalars are schema-legal (check_record) — the
                # batchers' trace_ids attribute rides through as-is.
                flat[k] = list(v)
            else:
                flat[k] = str(v)
        line = None
        if self._jsonl is not None:
            # A thread bound to a request's cross-process context stamps
            # the REQUEST trace id (and, on root spans, the remote parent)
            # instead of the tracer's run id — the field obs/merge.py
            # groups on.  ctx belongs to the RECORDING thread: add_span
            # callers (batcher workers) carry request identity via attrs.
            ctx = getattr(self._tls, "ctx", None)
            rec = {
                "schema": SCHEMA_VERSION,
                "kind": "span",
                "service": self.service,
                "trace_id": ctx[0] if ctx is not None else self.trace_id,
                "span_id": span_id,
                "parent_id": parent_id,
                "name": name,
                "time": round(self._epoch0 + t0, 6),
                "dur_s": round(t1 - t0, 9),
                "pid": self._pid,
                "tid": tid,
                **flat,
            }
            if ctx is not None and parent_id == 0 and ctx[1]:
                rec["remote_parent"] = ctx[1]
            line = json.dumps(rec) + "\n"
        ev = {
            "name": name,
            "ph": "X",
            "ts": (t0 - self._t0) * 1e6,  # microseconds, trace-relative
            "dur": max((t1 - t0) * 1e6, 0.0),
            # cached: getpid() is a real syscall (~17 us under gVisor) and
            # this is the per-span hot path
            "pid": self._pid,
            "tid": tid,
        }
        if flat:
            ev["args"] = flat
        with self._lock:
            if len(self._events) < self.max_events:
                self._events.append(ev)
            else:
                self.dropped_events += 1
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            # Re-check under the lock: close() nulls _jsonl while in-flight
            # request threads may still be exiting spans (the serve
            # frontend stops admission before the tracer, but queued work
            # finishes after).
            if line is not None and self._jsonl is not None:
                self._jsonl.write(line)
                # Flush at most every 0.25 s: live enough for obs_tail -f,
                # without one fsync-ish syscall per span on the hot path
                # (per-span flush measured ~2.5% of a 41 ms CPU step).
                if t1 - self._jsonl_flushed > 0.25:
                    self._jsonl.flush()
                    self._jsonl_flushed = t1

    # -- exporters ---------------------------------------------------------

    def chrome_events(self) -> List[dict]:
        """The buffered Chrome events plus process/thread metadata."""
        if not self.enabled:
            return []
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
        pid = self._pid  # must match the per-event pid (cached at init)
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"ddlpc_{self.service}"},
            }
        ]
        for tid, tname in sorted(names.items()):
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        return meta + events

    def flush(self, chrome_path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace (``{"traceEvents": [...]}``) and flush the
        JSONL stream.  Safe to call repeatedly (each call rewrites the whole
        file — span volume is bounded by ``max_events``).  Returns the path
        written, or None when disabled / no path configured."""
        if not self.enabled:
            return None
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.flush()
        path = chrome_path or self.chrome_path
        if path is None:
            return None
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "metadata": {
                "service": self.service,
                "trace_id": self.trace_id,
                "dropped_events": self.dropped_events,
            },
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        # Rename-atomic, not fsynced: flush() runs on live cadences and a
        # trace is diagnostics, not state — readers never see a torn
        # trace.json, and that is the whole contract here.
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        if not self.enabled:
            return
        self.flush()
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None
