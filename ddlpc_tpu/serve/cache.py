"""Content-addressed response cache for the fleet router.

Repeated-scene traffic (the realistic heavy-traffic shape for aerial
imagery — the same survey tiles requested over and over) recomputes a
full forward pass per request even though the answer is a pure function
of (input bytes, serving checkpoint step, quantization mode).  This
module caches that function: the router hashes the request body together
with the fleet's serving step and quant mode, and answers repeats from
memory without touching a replica.

Design constraints, in order:

- **Correctness over hit rate.**  The serving step and quant mode are
  part of the key, so a stale entry can never answer for new weights
  even if invalidation were missed.  Invalidation (on any reload that
  changes the serving step, forward or rollback) exists to bound memory
  and keep the stats honest, not as the correctness mechanism.
- **Bounded by bytes, not entries.**  Tile responses are a few hundred
  KB of logits; an entry count says nothing about memory.  LRU eviction
  runs until the payload total is back under ``max_bytes``.
- **jax-free.**  Pure stdlib (hashlib / threading / OrderedDict) — the
  cache lives in the router tier and must keep it host-tier
  (`analysis/tiers.py`).

Only 200 responses are cached: errors and shed responses are transient
routing outcomes, not values of the pure function above.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from ddlpc_tpu.analysis import lockcheck

# (status, content_type, payload) — the router's Response triple.
Response = Tuple[int, str, bytes]


def response_key(
    body: bytes,
    step: int,
    quant_mode: str,
    lineage_id: Optional[str] = None,
) -> str:
    """Content address of a predict response.

    sha256 over the raw request bytes plus the serving identity
    (checkpoint step + quantization mode + lineage id when the fleet
    reports one).  Any component changing yields a different key, so
    mixed-step fleets mid-reload can simply decline to cache rather than
    risk cross-step answers — and two RUNS that happen to share a step
    number never share cache entries (the lineage id is per-save).
    ``lineage_id=None`` reproduces the pre-lineage key, so caches warm
    under old checkpoints stay valid across an upgrade.
    """
    h = hashlib.sha256()
    h.update(body)
    h.update(b"\x00step=%d" % int(step))
    h.update(b"\x00quant=" + quant_mode.encode("utf-8", "replace"))
    if lineage_id is not None:
        h.update(b"\x00lineage=" + lineage_id.encode("utf-8", "replace"))
    return h.hexdigest()


@lockcheck.guarded
class ResponseCache:
    """Byte-bounded LRU of predict responses, keyed by content address.

    Thread-safe; every public method takes the one internal lock.  The
    router calls :meth:`get` / :meth:`put` on the dispatch path and
    :meth:`invalidate` from reload/rollback notifications, so all three
    must stay O(1)-ish — eviction amortizes over the puts that caused
    the growth.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = lockcheck.lock("ResponseCache._lock")
        self._entries: "OrderedDict[str, Response]" = OrderedDict()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self._hits = 0  # guarded-by: _lock
        self._misses = 0  # guarded-by: _lock
        self._evictions = 0  # guarded-by: _lock
        self._invalidations = 0  # guarded-by: _lock

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    def get(self, key: str) -> Optional[Response]:
        """Return the cached response for ``key``, or None.

        A hit moves the entry to most-recently-used; a miss is counted
        so hit-rate math needs no caller bookkeeping.
        """
        with self._lock:
            resp = self._entries.get(key)
            if resp is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return resp

    def put(self, key: str, response: Response) -> bool:
        """Cache a response; returns True if stored.

        Non-200 responses, payloads larger than the whole budget, and
        disabled caches are all no-ops (not errors): the dispatch path
        calls put unconditionally on fresh responses and this is where
        the policy lives.
        """
        status, _ctype, payload = response
        size = len(payload)
        if status != 200 or not self.enabled or size > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[2])
            self._entries[key] = response
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _k, (_s, _c, victim) = self._entries.popitem(last=False)
                self._bytes -= len(victim)
                self._evictions += 1
            return True

    def invalidate(self, reason: str = "") -> int:
        """Drop every entry; returns how many were dropped.

        Called fleet-wide whenever the serving step changes — a
        completed rolling reload and a rollback after an aborted one
        both land here (the step moved either way).
        """
        del reason  # callers log it; the cache only counts
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            if n:
                self._invalidations += 1
            return n

    def stats(self) -> Dict[str, float]:
        """Flat snapshot for JSONL records and /metrics scrapes."""
        with self._lock:
            total = self._hits + self._misses
            return {
                "cache_entries": len(self._entries),
                "cache_bytes": self._bytes,
                "cache_max_bytes": self.max_bytes,
                "cache_hits": self._hits,
                "cache_misses": self._misses,
                "cache_evictions": self._evictions,
                "cache_invalidations": self._invalidations,
                "cache_hit_rate": (self._hits / total) if total else 0.0,
            }
