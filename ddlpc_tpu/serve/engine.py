"""Inference engine: restore once, compile per shape bucket, serve forever.

Owns the three things every inference caller needs and no caller should
rebuild per request:

- the restored model + TrainState (restored ONCE; ``reload()`` hot-swaps
  params from a newer checkpoint without dropping in-flight work — requests
  that already snapshotted the old state finish on it, later ones see the
  new one; the swap is a single lock-guarded reference assignment);
- a shape-bucketed cache of jitted forward functions: batch sizes round up
  to the next power of two, so an arbitrary mix of request sizes compiles
  at most ``log2(max_bucket)+1`` executables per tile geometry instead of
  one per distinct batch size (the pjit serving lesson: shape-stable
  executables are what keep the accelerator busy under ragged load);
- the overlap-blended sliding-window tiler that turns an arbitrary-size
  scene into fixed-tile model calls — hoisted here from ``predict.py`` so
  the batch CLI and the server share one tested stitching path.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ddlpc_tpu.resilience import chaos as _chaos_mod
from ddlpc_tpu.serve import quantized as _quantized

PyTree = object


def _blend_window(tile: Tuple[int, int]) -> np.ndarray:
    """[th, tw] separable triangular weights, strictly positive, peaked at
    the window center — overlapping windows cross-fade instead of seaming."""

    def ramp(n: int) -> np.ndarray:
        x = np.arange(n, dtype=np.float32)
        return np.minimum(x + 1.0, n - x) / ((n + 1) / 2)

    return np.outer(ramp(tile[0]), ramp(tile[1])).astype(np.float32)


def window_plan(
    image: np.ndarray, tile: Tuple[int, int], overlap: float
) -> Tuple[np.ndarray, List[Tuple[int, int]], Tuple[int, int]]:
    """(padded image, window origins, original (h, w)) for a tiling pass.

    Covers the scene with ``tile``-sized windows at stride
    ``tile·(1-overlap)`` (the last row/column snaps flush to the edge, so
    coverage is exact without padding unless the scene is smaller than one
    tile).
    """
    if not 0.0 <= overlap < 1.0:
        # A negative overlap would stride past the tile, leaving wsum==0
        # gaps whose 0/0 logits silently argmax to class 0.
        raise ValueError(f"overlap must be in [0, 1), got {overlap}")
    th, tw = tile
    h, w = image.shape[:2]
    pad_h, pad_w = max(th - h, 0), max(tw - w, 0)
    if pad_h or pad_w:
        image = np.pad(image, ((0, pad_h), (0, pad_w), (0, 0)))
    H, W = image.shape[:2]

    def starts(extent: int, size: int, stride: int) -> List[int]:
        out = list(range(0, extent - size + 1, stride))
        if out[-1] != extent - size:
            out.append(extent - size)
        return out

    sh = max(int(th * (1.0 - overlap)), 1)
    sw = max(int(tw * (1.0 - overlap)), 1)
    origins = [(y, x) for y in starts(H, th, sh) for x in starts(W, tw, sw)]
    return image, origins, (h, w)


class Stitcher:
    """Incremental overlap-blend accumulator: feed per-window logits as they
    arrive, hold only the [H, W, C] accumulator — never the full set of
    window logits (on a 10k² scene at 0.25 overlap that buffer would be
    ~1.8× the scene's own logits on top of it)."""

    def __init__(
        self,
        tile: Tuple[int, int],
        padded_shape: Tuple[int, int],
        out_shape: Tuple[int, int],
    ):
        self.tile = tile
        self.padded_shape = padded_shape
        self.out_shape = out_shape
        self._weight = _blend_window(tile)
        self._acc: Optional[np.ndarray] = None
        self._wsum = np.zeros((*padded_shape, 1), np.float32)

    def add(self, origin: Tuple[int, int], tile_logits: np.ndarray) -> None:
        th, tw = self.tile
        y, x = origin
        if self._acc is None:
            self._acc = np.zeros(
                (*self.padded_shape, tile_logits.shape[-1]), np.float32
            )
        self._acc[y : y + th, x : x + tw] += np.asarray(
            tile_logits, np.float32
        ) * self._weight[..., None]
        self._wsum[y : y + th, x : x + tw, 0] += self._weight

    def finish(self) -> np.ndarray:
        assert self._acc is not None, "no windows were added"
        h, w = self.out_shape
        return (self._acc / self._wsum)[:h, :w]


def stitch_windows(
    origins: Sequence[Tuple[int, int]],
    window_logits: Sequence[np.ndarray],
    tile: Tuple[int, int],
    padded_shape: Tuple[int, int],
    out_shape: Tuple[int, int],
) -> np.ndarray:
    """Blend per-window logits back into full-scene logits [h, w, C]."""
    st = Stitcher(tile, padded_shape, out_shape)
    for origin, tile_logits in zip(origins, window_logits):
        st.add(origin, tile_logits)
    return st.finish()


def sliding_window_logits(
    logits_fn: Callable[..., np.ndarray],
    state,
    image: np.ndarray,
    tile: Tuple[int, int],
    overlap: float = 0.25,
    batch: int = 8,
) -> np.ndarray:
    """Full-scene logits [H, W, C] for an arbitrary-size image [H, W, c].

    Runs the compiled ``logits_fn`` on fixed-size window batches and blends
    overlaps with triangular weights.  This is the synchronous one-shot
    path (the predict CLI); the serving engine runs the same plan/stitch
    with windows routed through the micro-batcher instead.
    """
    padded, origins, (h, w) = window_plan(image, tile, overlap)
    th, tw = tile
    # Blend each batch into the accumulator as it completes: peak memory is
    # the scene accumulator + ONE batch of logits, not every window's.
    st = Stitcher(tile, padded.shape[:2], (h, w))
    for i in range(0, len(origins), batch):
        chunk = origins[i : i + batch]
        windows = np.stack([padded[y : y + th, x : x + tw] for y, x in chunk])
        valid = len(chunk)
        if valid < batch:  # pad to the compiled batch size
            windows = np.concatenate(
                [windows, np.repeat(windows[-1:], batch - valid, axis=0)]
            )
        logits = np.asarray(logits_fn(state, windows), np.float32)[:valid]
        for origin, tile_logits in zip(chunk, logits):
            st.add(origin, tile_logits)
    return st.finish()


def _bucket(n: int, cap: int) -> int:
    """Smallest power of two >= n, clipped to cap (callers split above it).

    Non-power-of-two caps get the bucket set {1, 2, 4, ..., cap}: the clip
    guarantees no executable ever exceeds the operator's batch cap."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


class InferenceEngine:
    """Restored checkpoint + shape-bucketed compiled forwards + hot reload.

    Thread-safe: ``forward_windows`` snapshots the state reference once per
    call, so a concurrent ``reload()`` never mixes parameter versions within
    one forward; the jit cache is dict-per-key under the same lock.
    """

    def __init__(
        self,
        cfg,
        model,
        state,
        channels: int,
        workdir: Optional[str] = None,
        max_bucket: int = 8,
        quantize: str = "off",
        quantize_activations: bool = False,
    ):
        self.cfg = cfg
        self.model = model
        self.channels = channels
        self.workdir = workdir
        self.tile: Tuple[int, int] = tuple(cfg.data.image_size)
        self.max_bucket = max(1, int(max_bucket))
        self.version = 0
        self.checkpoint_step: Optional[int] = None
        # Lineage of the serving checkpoint (ISSUE 17): set by from_workdir
        # and swapped atomically with (state, qstate) on reload, so healthz
        # and the X-DDLPC-Model-Step header always describe the weights
        # actually answering.  None until a restore supplies one (pre-
        # lineage checkpoints arrive as the explicit unknown marker, never
        # absent — train/checkpoint.py's degradation contract).
        self.lineage: Optional[dict] = None
        self.last_restore_s: Optional[float] = None
        self._lock = threading.Lock()
        self._state = state
        # Weight quantization (serve/quantized.py): int8/bf16 params with
        # per-leaf max-abs scales, computed ONCE here (and per reload) —
        # forwards carry the quantized tree, dequant fused into the jitted
        # program; the fp32 restore target stays host-side.
        self.quantize_mode = _quantized.check_mode(quantize)
        self.quantize_activations = bool(quantize_activations)
        self._qstate = None
        if quantize != "off":
            self._qstate = self._quantize(state)
        # (batch_bucket, th, tw, c) -> jitted logits fn.  Each key owns its
        # own jax.jit wrapper; len(cache) is the number of live executables.
        self._jit_cache: Dict[Tuple[int, int, int, int], Callable] = {}
        self.forward_calls = 0
        # Optional jit-cache hit/miss counters (attach_registry): the
        # continuous-batching work needs to SEE whether ragged traffic is
        # reusing executables or compiling its way through the bucket set.
        self._cache_hits = None
        self._cache_misses = None

    def _quantize(self, state):
        """Quantize ``state`` for serving (off-lock; callers swap the
        result in under the lock).  Scales are recomputed here and ONLY
        here — once per restore/reload, never per request."""
        return _quantized.quantize_state(state, self.quantize_mode)

    def hbm_bytes(self) -> Dict[str, int]:
        """Resident inference-state bytes by kind, for the SERVING tree
        the forwards actually carry (the quantized one when quantization
        is on) — what ``ddlpc_hbm_bytes{kind}`` reports on /metrics."""
        with self._lock:
            tree = self._qstate if self._qstate is not None else self._state
        return _quantized.state_nbytes(tree)

    def attach_registry(self, registry) -> None:
        """Publish ``ddlpc_serve_jit_cache_{hits,misses}_total{bucket}``
        counters and the ``ddlpc_hbm_bytes{kind}`` gauges into a
        MetricsRegistry (obs/registry.py) — wired by the ServingFrontend
        so the shape-bucketed cache's behavior AND the quantized rollout's
        HBM footprint are visible on the content-negotiated ``/metrics``."""
        self._cache_hits = registry.counter(
            "ddlpc_serve_jit_cache_hits_total",
            "forward_windows calls served by an existing executable, by "
            "batch bucket.",
            labelnames=("bucket",),
        )
        self._cache_misses = registry.counter(
            "ddlpc_serve_jit_cache_misses_total",
            "forward_windows calls that created a new jit wrapper "
            "(compile on first execution), by batch bucket.",
            labelnames=("bucket",),
        )
        self._hbm_gauge = registry.gauge(
            "ddlpc_hbm_bytes",
            "Resident inference-state bytes (the quantized tree when "
            "weight quantization is on), by kind.",
            labelnames=("kind",),
        )
        self._publish_hbm()

    def _publish_hbm(self) -> None:
        gauge = getattr(self, "_hbm_gauge", None)
        if gauge is None:
            return
        for kind, nbytes in self.hbm_bytes().items():
            gauge.set(float(nbytes), kind=kind)

    # ---- construction ------------------------------------------------------

    @classmethod
    def from_workdir(
        cls,
        workdir: str,
        max_bucket: int = 8,
        echo: bool = True,
        quantize: str = "off",
        quantize_activations: bool = False,
    ) -> "InferenceEngine":
        """Restore a training run's newest checkpoint into an engine.

        Input channel count comes from the checkpoint metadata (the Trainer
        records what the dataset actually had) — NOT a hardcoded 3, which
        made non-RGB checkpoints unrestorable (ADVICE r1).
        """
        import jax

        from ddlpc_tpu.config import ExperimentConfig
        from ddlpc_tpu.models import build_model
        from ddlpc_tpu.parallel.train_step import create_train_state
        from ddlpc_tpu.train import checkpoint as ckpt
        from ddlpc_tpu.train.optim import build_optimizer

        with open(os.path.join(workdir, "config.json")) as f:
            cfg = ExperimentConfig.from_json(f.read())
        ckpt_dir = os.path.join(workdir, "checkpoints")
        step = ckpt.latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
        try:
            meta = ckpt.peek_metadata(ckpt_dir, step)
        except (OSError, *ckpt.CorruptionError):
            # A corrupt newest sidecar must not abort cold start: the
            # restore below quarantines/falls back on its own, and the
            # restored metadata re-supplies the channel count.
            meta = {}
        channels = int(meta.get("input_channels", 3))
        # Inference is single-device: no mesh axis for BN stats.
        model = build_model(cfg.model, norm_axis_name=None)
        # Dummy schedule horizon: only the optimizer state STRUCTURE matters
        # for restore, and decaying schedules would refuse total_steps=None.
        tx = build_optimizer(cfg.train, total_steps=1)
        h, w = cfg.data.image_size
        target = create_train_state(
            model, tx, jax.random.key(0), (1, h, w, channels)
        )
        state, meta = ckpt.restore_checkpoint(ckpt_dir, target)
        # The restore may have fallen back past the step peeked above
        # (target supplies structure only, so a channel-count guess never
        # constrains the restored leaves) — trust the restored metadata.
        channels = int(meta.get("input_channels", channels))
        if echo:
            print(
                f"restored step {meta.get('step')} (epoch {meta.get('epoch')})"
            )
        eng = cls(cfg, model, state, channels, workdir=workdir,
                  max_bucket=max_bucket, quantize=quantize,
                  quantize_activations=quantize_activations)
        eng.checkpoint_step = meta.get("step")
        eng.lineage = meta.get("lineage")
        return eng

    # ---- state management --------------------------------------------------

    @property
    def state(self):
        with self._lock:
            return self._state

    def reload(self, workdir: Optional[str] = None, step=None) -> dict:
        """Hot-swap params from the newest checkpoint in ``workdir``.

        The restore happens OFF-lock against the current state's structure;
        only the final reference swap takes the lock, so in-flight forwards
        (which snapshotted the old reference) are never torn mid-call.
        Restores either checkpoint format through the one dispatching
        reader (train/checkpoint.py): a trainer that switched to the
        chunked writer hot-reloads into a serving engine started on a
        legacy blob, and vice versa.  The returned metadata gains
        ``restore_seconds``/``restore_format`` so the /reload response
        shows what the swap actually cost.
        """
        import time as _time

        from ddlpc_tpu.train import checkpoint as ckpt

        workdir = workdir or self.workdir
        if workdir is None:
            raise ValueError("no workdir to reload from")
        ckpt_dir = os.path.join(workdir, "checkpoints")
        monkey = _chaos_mod.active()
        if monkey is not None:
            # reload_corrupt@K: flip a byte of the newest blob before the
            # Kth reload — the reader quarantines and falls back, and a
            # rolling fleet reload must abort fleet-wide on that signal.
            monkey.on_serve_reload(ckpt_dir)
        t0 = _time.perf_counter()
        state, meta = ckpt.restore_checkpoint(ckpt_dir, self.state, step=step)
        # Re-quantize BEFORE the swap (still off-lock): scales are
        # per-checkpoint data, and in-flight forwards must never see new
        # fp32 state paired with old int8 weights — the lock below swaps
        # (state, qstate) as one unit.
        qstate = (
            self._quantize(state) if self.quantize_mode != "off" else None
        )
        restore_s = _time.perf_counter() - t0
        resolved = meta.get("step") if meta.get("step") is not None else step
        fmt = None
        if resolved is not None:
            try:
                _, fmt = ckpt.checkpoint_path(ckpt_dir, int(resolved))
            except FileNotFoundError:
                pass  # pruned between restore and stat — timing still valid
        with self._lock:
            # (state, qstate, lineage) swap as ONE unit: the re-quantized
            # tree above was computed from THIS state, and provenance must
            # never describe weights other than the ones serving.
            self._state = state
            self._qstate = qstate
            self.version += 1
            self.checkpoint_step = meta.get("step")
            self.lineage = meta.get("lineage")
            self.last_restore_s = restore_s
        self._publish_hbm()
        meta = dict(meta, restore_seconds=round(restore_s, 4))
        if self.quantize_mode != "off":
            meta["quantize"] = self.quantize_mode
        if fmt is not None:
            meta["restore_format"] = fmt
        return meta

    # ---- compiled forward --------------------------------------------------

    def _logits_fn(self, key: Tuple[int, int, int, int]) -> Callable:
        with self._lock:
            fn = self._jit_cache.get(key)
            hit = fn is not None
            if fn is None:
                if self.quantize_mode != "off":
                    fn = _quantized.make_quantized_logits_fn(
                        self.model,
                        self.quantize_mode,
                        quantize_activations=self.quantize_activations,
                    )
                else:
                    from ddlpc_tpu.parallel.train_step import make_logits_fn

                    fn = make_logits_fn(self.model)
                self._jit_cache[key] = fn
        counter = self._cache_hits if hit else self._cache_misses
        if counter is not None:
            counter.inc(bucket=str(key[0]))
        return fn

    @property
    def compiled_shapes(self) -> int:
        with self._lock:
            return len(self._jit_cache)

    def forward_windows(self, windows) -> np.ndarray:
        """Logits [N, th, tw, C] for N fixed-size windows [N, th, tw, c].

        N is padded up to the next power-of-two bucket (repeating the last
        window) so ragged request mixes reuse a handful of executables;
        batches above ``max_bucket`` split into bucket-size chunks.
        """
        windows = np.asarray(windows, np.float32)
        if windows.ndim == 3:
            windows = windows[None]
        n = len(windows)
        if n == 0:
            raise ValueError("forward_windows needs at least one window")
        monkey = _chaos_mod.active()
        if monkey is not None:
            # Serve-side fault injection (resilience/chaos.py): kill, stall,
            # or raise here so the injected failure rides the REAL error
            # path — batcher fails the batch, frontend answers 500, the
            # fleet router's breaker counts it.  Inert when unset.
            monkey.on_serve_forward()
        # One snapshot: never mixes reload versions (quantized forwards
        # carry the quantized tree; the fp32 state is the restore target).
        with self._lock:
            state = (
                self._qstate if self._qstate is not None else self._state
            )
        outs = []
        for i in range(0, n, self.max_bucket):
            chunk = windows[i : i + self.max_bucket]
            b = _bucket(len(chunk), self.max_bucket)
            if b > len(chunk):
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], b - len(chunk), axis=0)]
                )
            key = (b, *chunk.shape[1:])
            fn = self._logits_fn(key)
            self.forward_calls += 1
            outs.append(
                np.asarray(fn(state, chunk), np.float32)[
                    : min(self.max_bucket, n - i)
                ]
            )
        return np.concatenate(outs) if len(outs) > 1 else outs[0]

    def warmup(self, up_to: Optional[int] = None) -> int:
        """Pre-compile every power-of-two bucket ≤ ``up_to`` (default: all)
        for the configured tile geometry, so the first real traffic never
        pays a compile.  Returns the number of live executables."""
        up_to = self.max_bucket if up_to is None else min(up_to, self.max_bucket)
        th, tw = self.tile
        b = 1
        while True:
            self.forward_windows(np.zeros((b, th, tw, self.channels), np.float32))
            if b >= up_to:
                break
            b <<= 1
        return self.compiled_shapes

    # ---- full-scene prediction --------------------------------------------

    def predict_logits(
        self, image: np.ndarray, overlap: float = 0.25, batch: int = 8
    ) -> np.ndarray:
        """Synchronous full-scene logits via the engine's compiled cache.

        Unlike the standalone :func:`sliding_window_logits` (which pads the
        ragged tail chunk up to ``batch`` for a fixed compiled size), the
        tail here goes to ``forward_windows`` unpadded — the engine's own
        power-of-two bucketing picks the smallest adequate executable.
        """
        padded, origins, (h, w) = window_plan(image, self.tile, overlap)
        th, tw = self.tile
        st = Stitcher(self.tile, padded.shape[:2], (h, w))
        for i in range(0, len(origins), batch):
            chunk = origins[i : i + batch]
            windows = np.stack(
                [padded[y : y + th, x : x + tw] for y, x in chunk]
            )
            for origin, tile_logits in zip(chunk, self.forward_windows(windows)):
                st.add(origin, tile_logits)
        return st.finish()

    def predict_classes(
        self, image: np.ndarray, overlap: float = 0.25, batch: int = 8
    ) -> np.ndarray:
        return np.argmax(
            self.predict_logits(image, overlap=overlap, batch=batch), axis=-1
        ).astype(np.int32)
