"""Serving observability: latency quantiles, queue depth, occupancy, rates.

Rides the same JSONL stream shape as training (`train/observability.py`
``MetricsLogger``): one flat JSON object per emit, so the tooling that tails
training metrics tails serving metrics unchanged.  Quantiles AND batch
occupancy come from bounded rings of recent observations (windowed, not
lifetime, so a load spike is visible in p99 — and a cold-start occupancy
ramp ages out instead of dragging the reported mean forever); rates
(requests/sec, tiles/sec) are measured over the interval since the previous
snapshot.

With a ``registry`` (obs/registry.py) every hook also updates the
Prometheus-side series (``ddlpc_serve_*``), so the text exposition on
``GET /metrics`` reflects live counters without a snapshot cycle.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np


class ServeMetrics:
    """Thread-safe counters + windowed latency histogram for the serve path.

    Hooked by the frontend (``record_request``: one call per scene request
    with its end-to-end latency and tile count — so ``requests_per_sec`` is
    scene throughput and ``tiles_per_sec`` is accelerator throughput, which
    differ for multi-window scenes) and by the batcher (batch occupancy,
    queue depth, sheds, deadline misses).
    """

    def __init__(self, window: int = 2048, registry=None):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=window)  # seconds, most-recent window
        # Per-priority-class latency rings (serve/cbatch.py): bulk tiling
        # work must be visible as ITS OWN tail, not a contaminant of the
        # interactive p99 the fleet router protects.
        self._lat_by_prio = {
            "interactive": deque(maxlen=window),
            "batch": deque(maxlen=window),
        }
        # Windowed like the latency ring: a day-old cold-start ramp must
        # not drag the reported occupancy permanently (the old lifetime
        # `_occupancy_sum` did exactly that).
        self._occ = deque(maxlen=window)
        self.requests = 0
        self.tiles = 0
        self.shed = 0
        self.shed_batch = 0  # bulk-class admissions shed (subset of shed)
        self.deadline_exceeded = 0
        self.batches = 0
        self.queue_depth = 0
        self.priority_depths = {"interactive": 0, "batch": 0}
        self.slot_busy: Dict[int, float] = {}
        # False until a priority-aware batcher reports per-class depths;
        # snapshot() then mirrors the single queue into interactive so a
        # coalesce-mode stream never contradicts itself (queue_depth=40,
        # queue_depth_interactive=0).
        self._prio_source = False
        self._t0 = time.monotonic()
        self._last_t = self._t0
        self._last_requests = 0
        self._last_tiles = 0
        # Prometheus-side series (optional; obs/registry.py).
        self._reg = None
        if registry is not None:
            self._reg = {
                "requests": registry.counter(
                    "ddlpc_serve_requests_total", "Scene requests completed."
                ),
                "tiles": registry.counter(
                    "ddlpc_serve_tiles_total", "Tiles forwarded for requests."
                ),
                "latency": registry.histogram(
                    "ddlpc_serve_request_latency_seconds",
                    "End-to-end scene request latency.",
                ),
                "shed": registry.counter(
                    "ddlpc_serve_shed_total", "Requests shed at admission."
                ),
                "deadline": registry.counter(
                    "ddlpc_serve_deadline_exceeded_total",
                    "Requests expired in queue past their deadline.",
                ),
                "batches": registry.counter(
                    "ddlpc_serve_batches_total", "Batched forwards executed."
                ),
                "occupancy": registry.gauge(
                    "ddlpc_serve_batch_occupancy",
                    "Occupancy (size/capacity) of the most recent batch.",
                ),
                "queue_depth": registry.gauge(
                    "ddlpc_serve_queue_depth", "Admission queue depth (tiles)."
                ),
                "priority_depth": registry.gauge(
                    "ddlpc_serve_priority_queue_depth",
                    "Admission queue depth by priority class "
                    "(continuous batcher).",
                    labelnames=("priority",),
                ),
                "slot_busy": registry.gauge(
                    "ddlpc_serve_slot_busy_fraction",
                    "Busy fraction of each continuous-batcher slot worker "
                    "over the last metrics window — the signal for sizing "
                    "`slots`.",
                    labelnames=("slot",),
                ),
            }

    # ---- recording hooks ---------------------------------------------------

    def record_request(
        self, latency_s: float, tiles: int = 1, priority: str = "interactive"
    ) -> None:
        with self._lock:
            self._lat.append(float(latency_s))
            ring = self._lat_by_prio.get(priority)
            if ring is not None:
                ring.append(float(latency_s))
            self.requests += 1
            self.tiles += int(tiles)
        if self._reg is not None:
            self._reg["requests"].inc()
            self._reg["tiles"].inc(int(tiles))
            self._reg["latency"].observe(float(latency_s))

    def record_batch(self, size: int, capacity: int) -> None:
        occ = size / max(capacity, 1)
        with self._lock:
            self.batches += 1
            self._occ.append(occ)
        if self._reg is not None:
            self._reg["batches"].inc()
            self._reg["occupancy"].set(occ)

    def record_shed(self, n: int = 1, priority: str = "interactive") -> None:
        with self._lock:
            self.shed += int(n)
            if priority == "batch":
                self.shed_batch += int(n)
        if self._reg is not None:
            self._reg["shed"].inc(int(n))

    def record_deadline(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_exceeded += int(n)
        if self._reg is not None:
            self._reg["deadline"].inc(int(n))

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
        if self._reg is not None:
            self._reg["queue_depth"].set(int(depth))

    def set_priority_queue_depth(self, depths: Dict[str, int]) -> None:
        """Per-priority-class depths (continuous batcher hook)."""
        with self._lock:
            self._prio_source = True
            self.priority_depths.update(
                {p: int(d) for p, d in depths.items()}
            )
        if self._reg is not None:
            for p, d in depths.items():
                self._reg["priority_depth"].set(int(d), priority=p)

    def priority_queue_depths(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.priority_depths)

    def set_slot_busy(self, fractions: Dict[int, float]) -> None:
        """Per-slot busy fractions (continuous batcher, emit cadence)."""
        with self._lock:
            self.slot_busy = {int(s): float(f) for s, f in fractions.items()}
        if self._reg is not None:
            for s, f in fractions.items():
                self._reg["slot_busy"].set(float(f), slot=str(s))

    # ---- readout -----------------------------------------------------------

    def occupancy(self) -> Optional[float]:
        """Windowed mean batch occupancy (None before the first batch).

        Cheap enough for every ``/healthz`` — the fleet router's
        occupancy-aware dispatch scrapes this once per second per replica,
        so it must not pay the full ``snapshot()`` percentile pass."""
        with self._lock:
            return float(np.mean(self._occ)) if self._occ else None

    def percentiles_ms(self) -> Dict[str, Optional[float]]:
        with self._lock:
            lat = list(self._lat)
        if not lat:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        p50, p95, p99 = np.percentile(np.asarray(lat) * 1000.0, [50, 95, 99])
        return {
            "p50_ms": round(float(p50), 3),
            "p95_ms": round(float(p95), 3),
            "p99_ms": round(float(p99), 3),
        }

    def snapshot(self, advance: bool = True) -> Dict[str, object]:
        """One flat record: cumulative counters + windowed quantiles +
        interval rates.

        ``advance=True`` (the periodic emitter, the bench) closes the rate
        interval; ``advance=False`` (ad-hoc readers like ``GET /metrics``)
        reads rates over the currently open interval WITHOUT resetting it,
        so scrapes cannot corrupt the emitter's cadence."""
        pct = self.percentiles_ms()
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._last_t, 1e-9)
            req_rate = (self.requests - self._last_requests) / dt
            tile_rate = (self.tiles - self._last_tiles) / dt
            if advance:
                self._last_t = now
                self._last_requests = self.requests
                self._last_tiles = self.tiles
            occupancy = float(np.mean(self._occ)) if self._occ else None
            by_prio = {}
            for p, ring in self._lat_by_prio.items():
                if ring:
                    by_prio[f"{p}_p99_ms"] = round(
                        float(np.percentile(np.asarray(ring) * 1e3, 99)), 3
                    )
            return {
                "kind": "serve",
                **pct,
                **by_prio,
                "requests": self.requests,
                "tiles": self.tiles,
                "shed": self.shed,
                "shed_batch": self.shed_batch,
                "deadline_exceeded": self.deadline_exceeded,
                "batches": self.batches,
                "batch_occupancy": (
                    round(occupancy, 4) if occupancy is not None else None
                ),
                "queue_depth": self.queue_depth,
                "queue_depth_interactive": (
                    self.priority_depths["interactive"]
                    if self._prio_source
                    else self.queue_depth
                ),
                "queue_depth_batch": self.priority_depths["batch"],
                "requests_per_sec": round(req_rate, 3),
                "tiles_per_sec": round(tile_rate, 3),
                "uptime_s": round(now - self._t0, 3),
            }

    def emit(self, logger) -> Dict[str, object]:
        """Write a snapshot onto a ``MetricsLogger`` JSONL stream."""
        snap = self.snapshot()
        logger.log(snap, echo=False)
        return snap
