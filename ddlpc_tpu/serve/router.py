"""Fleet routing frontend: health-aware dispatch, retry, hedging, breakers.

The single-process serving stack (server.py) has no answer to a replica
dying, hanging, or reloading mid-traffic; this module is the routing tier
that makes a FLEET of those processes look like one reliable endpoint
(ISSUE 10 tentpole; ROADMAP "multi-replica router" item):

- **occupancy-aware dispatch** — every request goes to the eligible
  replica with the least work (router-side in-flight + the queue depth
  scraped from each replica's ``/healthz``, which carries queue depth and
  batch occupancy exactly so this tier never parses full ``/metrics``);
- **retry on another replica** — a per-attempt timeout or a 5xx answer
  retries on a *different* replica with full-jitter backoff
  (``uniform(0, base·2^(attempt-1))`` — the supervisor's backoff shape at
  request scale);
- **hedged requests** — after ``hedge_ms`` without an answer a duplicate
  is dispatched to a second replica; the first answer wins and the loser
  is cancelled (fake replicas honor the cancel event; HTTP losers get
  their connection closed under them);
- **per-replica circuit breaker** — error-rate latch with half-open
  probing, the ``obs/health.py`` latch/re-arm pattern applied to a
  replica instead of a queue: trip open on a sustained error rate, admit
  bounded probes after a cooldown, close on consecutive probe successes;
- **graceful drain** — stop dispatching to one replica, wait for its
  in-flight requests to finish; the primitive under both replica restart
  and the rolling hot-reload (serve/fleet.py).

Transport is abstracted behind :class:`ReplicaClient` so the routing
logic unit-tests against in-process fakes; :class:`HTTPReplicaClient` is
the real one (stdlib ``http.client``, one connection per attempt —
serving is engine-bound, not socket-bound).  Everything the router does
is accounted: ``ddlpc_router_*`` metrics on the registry and flat
``kind="router"`` records on ``<fleet_dir>/router.jsonl``.

Deliberately jax-free: the router process babysits replicas that pay the
jax import; it must never pay one itself.
"""

from __future__ import annotations

import http.client
import json
import math
import queue
import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs

from ddlpc_tpu.analysis import lockcheck
from ddlpc_tpu.config import FleetConfig
from ddlpc_tpu.obs import lineage as obs_lineage
from ddlpc_tpu.obs.health import HealthMonitor, SLOTracker
from ddlpc_tpu.obs.registry import MetricsRegistry
from ddlpc_tpu.obs.tracing import (
    TRACEPARENT_HEADER,
    format_traceparent,
    new_span_hex,
    new_trace_id,
)
from ddlpc_tpu.serve.cache import ResponseCache, response_key

# (status, content-type, body).  The HTTP client appends a 4th element —
# the replica's X-DDLPC-Model-Step header — so consumers unpack with
# ``[:3]``; fakes returning bare 3-tuples stay valid.
Response = Tuple[int, str, bytes]


class ReplicaError(RuntimeError):
    """Transport-level attempt failure: connect refused, socket timeout,
    torn read — anything that never produced an HTTP status."""


class NoReplicasAvailable(RuntimeError):
    """No eligible replica (all dead, draining, or breaker-open)."""


def _priority_of(query: str) -> str:
    """Priority class of a request from its query string.  Unknown values
    fall back to interactive for ROUTING policy only — the replica's
    frontend still 400s them, so a typo cannot silently become bulk."""
    if not query:
        return "interactive"
    p = parse_qs(query).get("priority", ["interactive"])[0]
    return p if p == "batch" else "interactive"


def _cache_bypass(query: str) -> bool:
    """Per-request cache opt-out: ``?cache=bypass`` skips both lookup and
    fill (the request is routed and measured exactly as with the cache
    off — what the perf arm compares against)."""
    if not query:
        return False
    return parse_qs(query).get("cache", [""])[0] == "bypass"


def _is_conn_refused(e: BaseException) -> bool:
    """Walk the exception chain for a ConnectionRefusedError.  Clients
    wrap transport errors (``ReplicaError ... from e``), so the refused
    signal — "nothing is listening on that port yet" — arrives as a
    ``__cause__``/``__context__`` link, not the top-level type."""
    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        if isinstance(cur, ConnectionRefusedError):
            return True
        seen.add(id(cur))
        cur = cur.__cause__ or cur.__context__
    return False


def _percentile(sorted_vals: Sequence[float], q: float) -> Optional[float]:
    """np.percentile(interpolation='linear') without numpy — the router
    stays light enough to import in a jax-free supervisor process."""
    if not sorted_vals:
        return None
    k = (len(sorted_vals) - 1) * q / 100.0
    f, c = math.floor(k), math.ceil(k)
    if f == c:
        return float(sorted_vals[int(k)])
    return float(sorted_vals[f] * (c - k) + sorted_vals[c] * (k - f))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


@lockcheck.guarded
class CircuitBreaker:
    """Per-replica error-rate latch with half-open probing.

    closed → (error rate ≥ ``error_rate`` over the last ``window``
    outcomes, once ``min_samples`` seen) → open → (``cooldown_s``
    elapsed) → half_open → (``close_after`` consecutive probe successes)
    → closed; any half-open probe failure re-opens.  The latch/re-arm
    shape is ``obs/health.py:QueueSaturationDetector``'s, applied to a
    replica's error stream instead of a queue ratio.

    ``acquire()`` is the side-effecting admission check (it performs the
    open→half_open transition and counts probe slots); ``available()`` is
    the side-effect-free filter the dispatcher uses to rank candidates.
    """

    def __init__(
        self,
        window: int = 16,
        min_samples: int = 8,
        error_rate: float = 0.5,
        cooldown_s: float = 2.0,
        half_open_probes: int = 1,
        close_after: int = 2,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str], None]] = None,
    ):
        if not 0.0 < error_rate <= 1.0:
            raise ValueError(f"error_rate must be in (0, 1], got {error_rate}")
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.error_rate = float(error_rate)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = max(1, int(half_open_probes))
        self.close_after = max(1, int(close_after))
        self._clock = clock
        self._on_transition = on_transition
        self._lock = lockcheck.lock("CircuitBreaker._lock")
        self.state = "closed"  # guarded-by: _lock
        self._outcomes: deque = deque(maxlen=self.window)  # guarded-by: _lock
        self._open_until = 0.0  # guarded-by: _lock
        self._probes_inflight = 0  # guarded-by: _lock
        self._probe_successes = 0  # guarded-by: _lock

    def _transition(self, to: str) -> None:
        self.state = to
        if self._on_transition is not None:
            try:
                self._on_transition(to)
            except Exception:
                pass  # accounting must never break dispatch

    def available(self) -> bool:
        """Could a request be admitted right now?  No side effects."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                return self._clock() >= self._open_until
            return self._probes_inflight < self.half_open_probes

    def acquire(self) -> bool:
        """Admit one request; half-open admission consumes a probe slot."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._clock() < self._open_until:
                    return False
                self._transition("half_open")
                self._probes_inflight = 0
                self._probe_successes = 0
            if self._probes_inflight < self.half_open_probes:
                self._probes_inflight += 1
                return True
            return False

    def release(self) -> None:
        """Give back an acquired admission WITHOUT an outcome (the attempt
        was cancelled — a hedge/retry loser).  Without this, a cancelled
        half-open probe would leak its slot and wedge the replica out of
        rotation forever."""
        with self._lock:
            if self.state == "half_open":
                self._probes_inflight = max(0, self._probes_inflight - 1)

    def record(self, ok: bool) -> None:
        """Account one completed attempt against this replica."""
        with self._lock:
            if self.state == "half_open":
                self._probes_inflight = max(0, self._probes_inflight - 1)
                if ok:
                    self._probe_successes += 1
                    if self._probe_successes >= self.close_after:
                        self._outcomes.clear()
                        self._transition("closed")
                else:
                    self._open_until = self._clock() + self.cooldown_s
                    self._transition("open")
                return
            if self.state == "open":
                return  # straggler from before the trip; already accounted
            self._outcomes.append(bool(ok))
            if len(self._outcomes) >= self.min_samples:
                errors = sum(1 for o in self._outcomes if not o)
                if errors / len(self._outcomes) >= self.error_rate:
                    self._outcomes.clear()
                    self._open_until = self._clock() + self.cooldown_s
                    self._transition("open")


# ---------------------------------------------------------------------------
# replica clients (transport abstraction)
# ---------------------------------------------------------------------------


class ReplicaClient:
    """What the router needs from one replica.  Subclasses: the HTTP
    client below (real fleet) and in-process fakes (tests).

    ``predict``'s ``traceparent`` keyword is only ever passed when the
    router has TRACING enabled (``FleetConfig.trace``) — pre-existing
    fakes with the old signature keep working untraced."""

    name: str = "?"

    def predict(
        self,
        body: bytes,
        query: str,
        timeout_s: float,
        cancel: Optional[threading.Event] = None,
        traceparent: Optional[str] = None,
    ) -> Response:
        raise NotImplementedError

    def healthz(self, timeout_s: float) -> dict:
        raise NotImplementedError

    def metrics_text(self, timeout_s: float) -> str:
        """Prometheus text exposition from the replica's ``/metrics`` —
        what the fleet TelemetryAggregator scrapes.  Optional: fakes that
        never meet an aggregator may skip it."""
        raise NotImplementedError

    def reload(self, payload: dict, timeout_s: float) -> Tuple[int, dict]:
        raise NotImplementedError


class HTTPReplicaClient(ReplicaClient):
    """stdlib http.client transport: one connection per attempt.

    ``cancel`` support is real but blunt: the router closes the attempt's
    connection from the winning thread, which fails the loser's blocked
    read immediately instead of letting it run to its socket timeout.
    """

    def __init__(self, name: str, host: str, port: int):
        self.name = name
        self.host = host
        self.port = int(port)
        # Live connections keyed by their attempt's cancel token, so a
        # cancel closes ONLY that attempt's socket — this client is shared
        # by every dispatch thread and the scrape loop, and tearing down a
        # sibling request's healthy connection would inject false failures
        # into the breaker.
        self._conns: Dict[int, http.client.HTTPConnection] = {}
        self._conns_lock = threading.Lock()

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[bytes],
        timeout_s: float,
        headers: Optional[dict] = None,
        cancel: Optional[threading.Event] = None,
    ) -> Tuple[int, str, bytes, Optional[str]]:
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=timeout_s
        )
        key = id(cancel) if cancel is not None else None
        if key is not None:
            with self._conns_lock:
                self._conns[key] = conn
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            # 4th element: the replica's X-DDLPC-Model-Step provenance
            # header (None when absent).  Response consumers unpack via
            # ``[:3]`` so 3-tuple fakes and this 4-tuple interchange.
            return (
                resp.status,
                resp.getheader("Content-Type", ""),
                data,
                resp.getheader(obs_lineage.MODEL_STEP_HEADER),
            )
        except Exception as e:
            raise ReplicaError(f"{self.name}: {type(e).__name__}: {e}") from e
        finally:
            if key is not None:
                with self._conns_lock:
                    self._conns.pop(key, None)
            try:
                conn.close()
            except Exception:
                pass

    def cancel_attempt(self, cancel: threading.Event) -> None:
        """Close the one connection registered under this attempt's cancel
        token: its blocked read fails immediately, nobody else's does."""
        with self._conns_lock:
            conn = self._conns.get(id(cancel))
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def predict(
        self, body, query, timeout_s, cancel=None, traceparent=None
    ) -> Response:
        path = "/predict" + (f"?{query}" if query else "")
        headers = {"Content-Type": "application/x-npy"}
        if traceparent:
            headers[TRACEPARENT_HEADER] = traceparent
        return self._request(
            "POST", path, body, timeout_s, headers=headers, cancel=cancel,
        )

    def metrics_text(self, timeout_s: float) -> str:
        """Prometheus text exposition (Accept negotiates it — obs/http.py)."""
        status, _, body = self._request(
            "GET", "/metrics", None, timeout_s,
            headers={"Accept": "text/plain"},
        )[:3]
        if status != 200:
            raise ReplicaError(f"{self.name}: /metrics returned {status}")
        return body.decode("utf-8", errors="replace")

    def healthz(self, timeout_s: float) -> dict:
        status, _, body = self._request(
            "GET", "/healthz", None, timeout_s
        )[:3]
        try:
            h = json.loads(body)
        except ValueError:
            raise ReplicaError(f"{self.name}: /healthz returned non-JSON")
        if not isinstance(h, dict):
            raise ReplicaError(f"{self.name}: /healthz returned {type(h)}")
        return h

    def reload(self, payload: dict, timeout_s: float) -> Tuple[int, dict]:
        status, _, body = self._request(
            "POST", "/reload", json.dumps(payload).encode(), timeout_s,
            headers={"Content-Type": "application/json"},
        )[:3]
        try:
            meta = json.loads(body) if body else {}
        except ValueError:
            meta = {"error": "non-JSON /reload response"}
        return status, meta


# ---------------------------------------------------------------------------
# router metrics
# ---------------------------------------------------------------------------


class RouterMetrics:
    """Counters + windowed latency ring for the routing tier, published as
    ``ddlpc_router_*`` on the registry and as flat ``kind="router"``
    snapshots on router.jsonl.  The acceptance bar is that every retry,
    hedge, and breaker transition is accounted — these counters are the
    ledger the fleet soak audits its fault schedule against."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 window: int = 4096):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=window)
        self.requests = 0
        self.errors_5xx = 0  # CLIENT-VISIBLE failures (the soak forbids them)
        self.attempts = 0
        self.retries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.batch_shed = 0  # bulk-class requests shed at the router
        self.breaker_opens = 0
        self.breaker_half_opens = 0
        self.breaker_closes = 0
        self.drains = 0
        self.readmissions = 0
        self.reloads_ok = 0
        self.reloads_aborted = 0
        self._t0 = time.monotonic()
        self._last_t = self._t0
        self._last_requests = 0
        self._reg = None
        if registry is not None:
            self._reg = {
                "requests": registry.counter(
                    "ddlpc_router_requests_total",
                    "Client requests answered by the router, by outcome.",
                    labelnames=("outcome",),
                ),
                "attempts": registry.counter(
                    "ddlpc_router_attempts_total",
                    "Replica attempts dispatched, by replica and reason.",
                    labelnames=("replica", "reason"),
                ),
                "retries": registry.counter(
                    "ddlpc_router_retries_total",
                    "Attempts re-dispatched to another replica, by cause.",
                    labelnames=("cause",),
                ),
                "hedges": registry.counter(
                    "ddlpc_router_hedges_total",
                    "Duplicate attempts dispatched for the latency tail.",
                ),
                "hedge_wins": registry.counter(
                    "ddlpc_router_hedge_wins_total",
                    "Requests answered by the hedged attempt.",
                ),
                "batch_shed": registry.counter(
                    "ddlpc_router_batch_shed_total",
                    "Bulk-class (?priority=batch) requests shed at the "
                    "router because every eligible replica's interactive "
                    "queue was at or above batch_shed_queue_depth.",
                ),
                "breaker": registry.counter(
                    "ddlpc_router_breaker_transitions_total",
                    "Circuit-breaker transitions, by replica and new state.",
                    labelnames=("replica", "to"),
                ),
                "drains": registry.counter(
                    "ddlpc_router_drains_total",
                    "Replica drains completed (restart or rolling reload).",
                ),
                "reloads": registry.counter(
                    "ddlpc_router_reloads_total",
                    "Rolling fleet reloads, by outcome.",
                    labelnames=("outcome",),
                ),
                "latency": registry.histogram(
                    "ddlpc_router_request_latency_seconds",
                    "End-to-end routed request latency.",
                ),
                "ready": registry.gauge(
                    "ddlpc_router_replicas_ready",
                    "Replicas currently eligible for dispatch.",
                ),
                "cache_hits": registry.counter(
                    "ddlpc_cache_hits_total",
                    "Predict requests answered from the response cache.",
                ),
                "cache_misses": registry.counter(
                    "ddlpc_cache_misses_total",
                    "Cacheable predict requests that missed the cache.",
                ),
                "cache_evictions": registry.counter(
                    "ddlpc_cache_evictions_total",
                    "Cache entries evicted by the LRU byte bound.",
                ),
                "cache_invalidations": registry.counter(
                    "ddlpc_cache_invalidations_total",
                    "Fleet-wide cache flushes (serving step changed).",
                ),
                "cache_bytes": registry.gauge(
                    "ddlpc_cache_bytes",
                    "Payload bytes currently held by the response cache.",
                ),
                "cache_entries": registry.gauge(
                    "ddlpc_cache_entries",
                    "Entries currently held by the response cache.",
                ),
                # Freshness SLOs (ISSUE 17).  Replicas with unknown
                # lineage are SKIPPED (their healthz shows the explicit
                # lineage_unknown marker) — an absent series, never a
                # fabricated age.
                "model_age": registry.gauge(
                    "ddlpc_serve_model_age_s",
                    "Per-replica serving-checkpoint age: newest durable "
                    "checkpoint's save time minus the serving one's "
                    "(replica=\"fleet\" is the worst live replica).",
                    labelnames=("replica",),
                ),
                "step_skew": registry.gauge(
                    "ddlpc_fleet_step_skew",
                    "max - min over live replicas' serving checkpoint "
                    "steps; nonzero marks a mixed-weights window.",
                ),
            }
        # Last cache totals pushed to the registry, so sync_cache can inc
        # the monotonic counters by delta (the cache keeps the totals).
        self._cache_seen = {
            "cache_hits": 0, "cache_misses": 0,
            "cache_evictions": 0, "cache_invalidations": 0,
        }

    def record_request(self, latency_s: float, ok: bool) -> None:
        with self._lock:
            self.requests += 1
            self._lat.append(float(latency_s))
            if not ok:
                self.errors_5xx += 1
        if self._reg is not None:
            self._reg["requests"].inc(outcome="ok" if ok else "error")
            self._reg["latency"].observe(float(latency_s))

    def record_attempt(self, replica: str, reason: str) -> None:
        with self._lock:
            self.attempts += 1
        if self._reg is not None:
            self._reg["attempts"].inc(replica=replica, reason=reason)

    def record_retry(self, cause: str) -> None:
        with self._lock:
            self.retries += 1
        if self._reg is not None:
            self._reg["retries"].inc(cause=cause)

    def record_hedge(self) -> None:
        with self._lock:
            self.hedges += 1
        if self._reg is not None:
            self._reg["hedges"].inc()

    def record_hedge_win(self) -> None:
        with self._lock:
            self.hedge_wins += 1
        if self._reg is not None:
            self._reg["hedge_wins"].inc()

    def record_batch_shed(self) -> None:
        with self._lock:
            self.batch_shed += 1
        if self._reg is not None:
            self._reg["batch_shed"].inc()

    def record_breaker(self, replica: str, to: str) -> None:
        with self._lock:
            if to == "open":
                self.breaker_opens += 1
            elif to == "half_open":
                self.breaker_half_opens += 1
            else:
                self.breaker_closes += 1
        if self._reg is not None:
            self._reg["breaker"].inc(replica=replica, to=to)

    def record_drain(self) -> None:
        with self._lock:
            self.drains += 1
        if self._reg is not None:
            self._reg["drains"].inc()

    def record_readmit(self) -> None:
        with self._lock:
            self.readmissions += 1

    def record_reload(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.reloads_ok += 1
            else:
                self.reloads_aborted += 1
        if self._reg is not None:
            self._reg["reloads"].inc(outcome="ok" if ok else "aborted")

    def set_ready(self, n: int) -> None:
        if self._reg is not None:
            self._reg["ready"].set(n)

    def set_model_age(self, replica: str, age_s: float) -> None:
        if self._reg is not None:
            self._reg["model_age"].set(float(age_s), replica=replica)

    def set_step_skew(self, skew: float) -> None:
        if self._reg is not None:
            self._reg["step_skew"].set(float(skew))

    def sync_cache(self, stats: Dict[str, float]) -> None:
        """Push a ResponseCache.stats() snapshot to the registry: gauges
        are set absolutely, counters advance by delta since last sync."""
        if self._reg is None:
            return
        self._reg["cache_bytes"].set(float(stats["cache_bytes"]))
        self._reg["cache_entries"].set(float(stats["cache_entries"]))
        for key in self._cache_seen:
            total = int(stats[key])
            delta = total - self._cache_seen[key]
            if delta > 0:
                self._reg[key].inc(delta)
            self._cache_seen[key] = total

    def snapshot(self, advance: bool = True) -> Dict[str, object]:
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._last_t, 1e-9)
            rate = (self.requests - self._last_requests) / dt
            if advance:
                self._last_t = now
                self._last_requests = self.requests
            lat = sorted(self._lat)
            return {
                "kind": "router",
                "requests": self.requests,
                "errors_5xx": self.errors_5xx,
                "attempts": self.attempts,
                "retries": self.retries,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "batch_shed": self.batch_shed,
                "breaker_opens": self.breaker_opens,
                "breaker_half_opens": self.breaker_half_opens,
                "breaker_closes": self.breaker_closes,
                "drains": self.drains,
                "readmissions": self.readmissions,
                "reloads_ok": self.reloads_ok,
                "reloads_aborted": self.reloads_aborted,
                "p50_ms": _round(_percentile(lat, 50)),
                "p95_ms": _round(_percentile(lat, 95)),
                "p99_ms": _round(_percentile(lat, 99)),
                "requests_per_sec": round(rate, 3),
                "uptime_s": round(now - self._t0, 3),
            }


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1000.0, 3)


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


class _Replica:
    """Router-side view of one replica: client + dispatch state."""

    def __init__(self, name: str, client: ReplicaClient,
                 breaker: CircuitBreaker):
        self.name = name
        self.client = client
        self.breaker = breaker
        self.ready = False  # supervisor-declared (process up + warmed)
        self.draining = False  # router-declared (drain/reload in progress)
        self.healthy = True  # scrape-declared (flips after N failed scrapes)
        self.inflight = 0  # router-side attempts outstanding
        self.queue_depth = 0  # scraped
        # Per-priority depths + quant mode (scraped from the same one
        # /healthz): what priority-aware dispatch/shedding and quantized
        # rolling reloads rank on.  Replicas predating the continuous
        # batcher report only the total; interactive then mirrors it.
        self.queue_depth_interactive = 0  # scraped
        self.queue_depth_batch = 0  # scraped
        self.quant_mode: Optional[str] = None  # scraped
        self.occupancy: Optional[float] = None  # scraped
        self.checkpoint_step: Optional[int] = None  # scraped
        self.version: Optional[int] = None  # scraped
        self.slot_busy: Optional[float] = None  # scraped (autoscaler signal)
        # Serving lineage (scraped): the literal marker string for
        # pre-lineage checkpoints — visible on /fleet, skipped by gauges.
        self.lineage_id: Optional[str] = None  # scraped
        self.lineage_saved_at: Optional[float] = None  # scraped
        self.scrape_fail_streak = 0
        # True once this replica has EVER answered anything (a successful
        # scrape or any HTTP response to an attempt).  Until then a
        # connection-refused is "still warming", not "failing": the
        # replica is scored ineligible without feeding its breaker, so a
        # scale-up can never open a breaker on a replica mid-launch.
        self.ever_ok = False

    def status(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "ready": self.ready,
            "draining": self.draining,
            "healthy": self.healthy,
            "breaker": self.breaker.state,
            "inflight": self.inflight,
            "queue_depth": self.queue_depth,
            "queue_depth_interactive": self.queue_depth_interactive,
            "queue_depth_batch": self.queue_depth_batch,
            "quant_mode": self.quant_mode,
            "occupancy": self.occupancy,
            "checkpoint_step": self.checkpoint_step,
            "version": self.version,
            "slot_busy": self.slot_busy,
            "lineage_id": self.lineage_id,
            "lineage_saved_at": self.lineage_saved_at,
        }


class _Attempt:
    __slots__ = ("replica", "cancel", "reason", "outcome", "thread", "t0")

    def __init__(self, replica: _Replica, reason: str):
        self.replica = replica
        self.reason = reason  # "primary" | "retry" | "hedge"
        self.cancel = threading.Event()
        self.outcome: Optional[Tuple[str, object]] = None
        self.thread: Optional[threading.Thread] = None
        self.t0 = time.monotonic()


@lockcheck.guarded
class FleetRouter:
    """Dispatch requests across replicas; the fleet's one client-facing
    brain.  Thread-safe; replicas come and go at runtime (the supervisor
    registers them as they pass readiness and removes them when their
    process dies).

    Lock order (enforced by analysis/lockcheck.py under
    ``DDLPC_LOCKCHECK=1``): ``FleetRouter._lock`` may be held while taking
    ``CircuitBreaker._lock`` (``_pick`` ranks and admits under the router
    lock); the reverse never happens — breaker callbacks
    (``_on_breaker``) log and count without touching the router lock."""

    def __init__(
        self,
        cfg: Optional[FleetConfig] = None,
        registry: Optional[MetricsRegistry] = None,
        logger=None,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        tracer=None,
    ):
        self.cfg = cfg or FleetConfig()
        self.registry = registry if registry is not None else MetricsRegistry()
        self.metrics = RouterMetrics(registry=self.registry)
        self.logger = logger  # MetricsLogger(basename="router") or None
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        # Distributed tracing (ISSUE 14): with an enabled Tracer each
        # dispatch mints a request trace id, records route_request +
        # per-attempt spans, and forwards the context to the replica on
        # the traceparent header.  None/disabled = zero-cost no-op.
        self.tracer = tracer
        # SLO layer: every routed request feeds the per-priority latency/
        # availability objectives; burn-rate alerts ride the health
        # monitor's fan-out (JSONL + ddlpc_alerts_total + /healthz).
        self.health = HealthMonitor(
            logger=logger, registry=self.registry, service="router"
        )
        self.slo = SLOTracker.from_fleet_config(
            self.cfg, registry=self.registry, monitor=self.health
        )
        # Content-addressed response cache (serve/cache.py): repeated
        # tiles answer from memory when the fleet serves one consistent
        # (step, quant) identity.  max_bytes=0 keeps every call a no-op.
        self.cache = ResponseCache(self.cfg.cache_max_bytes)
        self._lock = lockcheck.lock("FleetRouter._lock")
        self._cache_step: Optional[int] = None  # guarded-by: _lock
        self._replicas: dict = {}  # guarded-by: _lock
        self._rr = 0  # guarded-by: _lock (round-robin tiebreaker)
        self._drain_cond = lockcheck.condition(lock=self._lock)
        self._stop = threading.Event()
        self._scraper: Optional[threading.Thread] = None
        self._emitter: Optional[threading.Thread] = None

    # -- replica registry ---------------------------------------------------

    def _new_breaker(self, name: str) -> CircuitBreaker:
        """ONE construction site: a readmitted replica's fresh breaker
        must never drift from a freshly added one's."""
        return CircuitBreaker(
            window=self.cfg.breaker_window,
            min_samples=self.cfg.breaker_min_samples,
            error_rate=self.cfg.breaker_error_rate,
            cooldown_s=self.cfg.breaker_cooldown_s,
            half_open_probes=self.cfg.breaker_half_open_probes,
            close_after=self.cfg.breaker_close_after,
            on_transition=lambda to, n=name: self._on_breaker(n, to),
        )

    def add_replica(
        self, name: str, client: ReplicaClient, ready: bool = True
    ) -> None:
        breaker = self._new_breaker(name)
        with self._lock:
            self._replicas[name] = _Replica(name, client, breaker)
            self._replicas[name].ready = ready
        self._log_event("replica_added", replica=name)
        self._publish_ready()

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)
        self._log_event("replica_removed", replica=name)
        self._publish_ready()

    def set_ready(self, name: str, ready: bool) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is not None:
                r.ready = ready
                if ready:
                    # A fresh process: forget the old error history.
                    r.healthy = True
                    r.scrape_fail_streak = 0
        self._publish_ready()

    def _on_breaker(self, name: str, to: str) -> None:
        self.metrics.record_breaker(name, to)
        self._log_event("breaker", replica=name, to=to)

    def _publish_ready(self) -> None:
        with self._lock:
            n = sum(
                1
                for r in self._replicas.values()
                if r.ready and not r.draining and r.healthy
            )
        self.metrics.set_ready(n)

    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def replica_status(self) -> List[Dict[str, object]]:
        with self._lock:
            return [r.status() for _, r in sorted(self._replicas.items())]

    # -- scraping -----------------------------------------------------------

    def scrape_once(self) -> None:
        """One /healthz pass over the fleet: queue depth + occupancy feed
        the dispatch score; ``unhealthy_after`` consecutive failures take
        a replica out of rotation until a scrape succeeds again."""
        with self._lock:
            targets = [r for r in self._replicas.values() if r.ready]
        for r in targets:
            try:
                h = r.client.healthz(self.cfg.scrape_timeout_s)
            except Exception as e:
                with self._lock:
                    r.scrape_fail_streak += 1
                    if _is_conn_refused(e) and not r.ever_ok:
                        # Mid-launch: the port isn't listening yet.  Take
                        # the replica out of rotation NOW (don't wait for
                        # unhealthy_after) but stay off its breaker — a
                        # warming replica has done nothing wrong.
                        if r.healthy:
                            self._log_event(
                                "replica_warming", replica=r.name,
                            )
                        r.healthy = False
                    elif r.scrape_fail_streak >= self.cfg.unhealthy_after:
                        if r.healthy:
                            self._log_event(
                                "replica_unhealthy", replica=r.name,
                                scrape_failures=r.scrape_fail_streak,
                            )
                        r.healthy = False
                continue
            with self._lock:
                if not r.healthy:
                    self._log_event("replica_recovered", replica=r.name)
                r.scrape_fail_streak = 0
                r.healthy = True
                r.ever_ok = True
                r.queue_depth = int(h.get("queue_depth") or 0)
                r.queue_depth_interactive = int(
                    h.get("queue_depth_interactive", h.get("queue_depth"))
                    or 0
                )
                r.queue_depth_batch = int(h.get("queue_depth_batch") or 0)
                r.quant_mode = h.get("quant_mode")
                occ = h.get("batch_occupancy")
                r.occupancy = float(occ) if occ is not None else None
                r.checkpoint_step = h.get("checkpoint_step")
                r.version = h.get("version")
                sb = h.get("slot_busy_fraction")
                r.slot_busy = float(sb) if sb is not None else None
                lid = h.get("lineage_id")
                r.lineage_id = lid if isinstance(lid, str) else None
                sv = h.get("lineage_saved_at")
                r.lineage_saved_at = (
                    float(sv)
                    if isinstance(sv, (int, float))
                    and not isinstance(sv, bool)
                    else None
                )
                if h.get("status") == "draining":
                    # The replica is shutting down on its own (SIGTERM):
                    # treat like a router-side drain — no new dispatch.
                    r.draining = True
        try:
            self._update_freshness()
        except Exception:
            pass  # freshness accounting must never break the scrape
        self._publish_ready()

    def _update_freshness(self) -> None:
        """Model-age + step-skew gauges from the latest scrape (ISSUE 17).

        Age = newest DURABLE checkpoint's ``saved_at`` (read from the
        sidecar via the stdlib path — no jax import in this tier) minus
        the replica's serving ``saved_at``.  Replicas whose lineage is
        the unknown marker are skipped — their healthz carries the
        explicit ``lineage_unknown`` string; the gauge never invents an
        age for them.  The ``replica="fleet"`` series is the worst live
        replica (the fleet is only as fresh as its stalest member)."""
        workdir = getattr(self.cfg, "workdir", None)
        newest = (
            obs_lineage.newest_checkpoint_lineage(workdir)
            if workdir
            else None
        )
        newest_saved = newest.get("saved_at") if newest else None
        with self._lock:
            live = [
                r for r in self._replicas.values()
                if r.ready and r.healthy and not r.draining
            ]
            rows = [(r.name, r.lineage_saved_at) for r in live]
            steps = [
                int(r.checkpoint_step)
                for r in live
                if r.checkpoint_step is not None
            ]
        ages = []
        for name, saved in rows:
            if newest_saved is None or saved is None:
                continue
            age = max(0.0, float(newest_saved) - float(saved))
            self.metrics.set_model_age(name, age)
            ages.append(age)
        if ages:
            self.metrics.set_model_age("fleet", max(ages))
        if steps:
            self.metrics.set_step_skew(float(max(steps) - min(steps)))

    def start(self) -> "FleetRouter":
        """Start the background scrape loop (and JSONL emitter if a
        logger is attached)."""
        if self._scraper is None and self.cfg.scrape_every_s > 0:
            self._scraper = threading.Thread(
                target=self._scrape_loop, name="router-scrape", daemon=True
            )
            self._scraper.start()
        if (
            self._emitter is None
            and self.logger is not None
            and self.cfg.metrics_every_s > 0
        ):
            self._emitter = threading.Thread(
                target=self._emit_loop, name="router-metrics", daemon=True
            )
            self._emitter.start()
        return self

    def _scrape_loop(self) -> None:
        while not self._stop.wait(self.cfg.scrape_every_s):
            try:
                self.scrape_once()
            except Exception:
                pass  # scraping must never kill the router

    def _emit_loop(self) -> None:
        while not self._stop.wait(self.cfg.metrics_every_s):
            self.emit()

    def emit(self) -> Dict[str, object]:
        snap = self.metrics.snapshot()
        if self.logger is not None:
            self.logger.log(snap, echo=False)
        # SLO status rides the same cadence: burn-rate detectors evaluate
        # (alerts fan out via the health monitor) and one flat
        # kind="slo" record lands per emit — the error-budget ledger.
        self.slo.check()
        if self.logger is not None and self.slo.enabled:
            try:
                self.logger.log(self.slo.status(), echo=False)
            except Exception:
                pass  # accounting must never break dispatch
        if self.cache.enabled:
            stats = self.cache.stats()
            self.metrics.sync_cache(stats)
            if self.logger is not None:
                try:
                    self.logger.log(
                        {"kind": "cache", **stats}, echo=False
                    )
                except Exception:
                    pass
        return snap

    def _log_event(self, event: str, **fields) -> None:
        if self.logger is None:
            return
        try:
            self.logger.log(
                {"kind": "router", "event": event, **fields}, echo=False
            )
        except Exception:
            pass

    def close(self) -> None:
        self._stop.set()
        for t in (self._scraper, self._emitter):
            if t is not None:
                t.join(timeout=5.0)
        if self.logger is not None:
            self.emit()

    # -- drain / readmit ----------------------------------------------------

    def drain(self, name: str, timeout_s: Optional[float] = None) -> bool:
        """Stop dispatching to ``name``, wait for its router-side in-flight
        count to reach zero.  Returns False on timeout (work still in
        flight — callers decide whether to proceed anyway)."""
        timeout_s = (
            self.cfg.drain_timeout_s if timeout_s is None else timeout_s
        )
        deadline = time.monotonic() + timeout_s
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return True
            r.draining = True
            while r.inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._publish_ready_locked()
                    return False
                self._drain_cond.wait(remaining)
        self.metrics.record_drain()
        self._log_event("drain", replica=name)
        self._publish_ready()
        return True

    def _publish_ready_locked(self) -> None:
        n = sum(
            1
            for r in self._replicas.values()
            if r.ready and not r.draining and r.healthy
        )
        self.metrics.set_ready(n)

    def readmit(self, name: str) -> None:
        """Put a drained replica back into dispatch with a clean slate
        (fresh weights or a fresh process deserve a fresh breaker)."""
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            r.draining = False
            r.breaker = self._new_breaker(name)
        self.metrics.record_readmit()
        self._log_event("readmit", replica=name)
        self._publish_ready()

    # -- dispatch -----------------------------------------------------------

    def _pick(self, exclude: Sequence[str]) -> Optional[_Replica]:
        """Least-loaded eligible replica, preferring ones not in
        ``exclude`` (a retry must land ELSEWHERE when anywhere else
        exists).  Score = router-side in-flight + scraped queue depth."""
        with self._lock:
            def eligible(r: _Replica) -> bool:
                return (
                    r.ready
                    and not r.draining
                    and r.healthy
                    and r.breaker.available()
                )

            ordered = [
                self._replicas[n] for n in sorted(self._replicas)
            ]
            pool = [
                r for r in ordered if eligible(r) and r.name not in exclude
            ]
            if not pool:
                pool = [r for r in ordered if eligible(r)]
            if not pool:
                return None
            # Rotate equal scores round-robin: stable sort by load keeps
            # the rotated order among ties, so an idle fleet spreads
            # instead of hammering whichever name sorts first.
            self._rr += 1
            k = self._rr % len(pool)
            pool = pool[k:] + pool[:k]
            pool.sort(key=lambda r: r.inflight + r.queue_depth)
            for r in pool:
                if r.breaker.acquire():
                    r.inflight += 1
                    return r
            return None

    def _finish_attempt(self, a: _Attempt, ok: Optional[bool]) -> None:
        """Attempt bookkeeping, run by the ATTEMPT THREAD on completion —
        not the dispatch loop, which may long since have answered the
        client off a faster attempt.  ``ok=None`` means cancelled (a
        hedge loser, a raced retry): the failure is the router's doing,
        so it must not poison the replica's breaker — but the admission
        it acquired (a half-open probe slot, possibly) must be
        released."""
        if ok is not None:
            a.replica.breaker.record(ok)
        else:
            a.replica.breaker.release()
        with self._lock:
            a.replica.inflight = max(0, a.replica.inflight - 1)
            self._drain_cond.notify_all()

    def _launch_waiting(
        self, body: bytes, query: str, reason: str,
        exclude: Sequence[str], done: "queue.Queue[_Attempt]",
        trace_id: Optional[str] = None,
    ) -> Optional["_Attempt"]:
        """`_launch` plus the bounded zero-eligible wait: a rolling
        reload's drain→readmit hand-off, a relaunch-readiness gap, and a
        breaker cooldown can momentarily leave NO eligible replica — a
        transient total-outage blip that should surface as tail latency,
        not a client-visible 503.  Admission and the no-pending retry
        pick ride it out the same way (per-pick bound)."""
        a = self._launch(body, query, reason, exclude, done, trace_id)
        if a is None and self.cfg.no_replica_wait_ms > 0:
            deadline = (
                time.monotonic() + self.cfg.no_replica_wait_ms / 1000.0
            )
            while a is None and time.monotonic() < deadline:
                self._sleep(self._rng.uniform(0.01, 0.04))
                a = self._launch(body, query, reason, exclude, done, trace_id)
        return a

    def _launch(
        self, body: bytes, query: str, reason: str,
        exclude: Sequence[str], done: "queue.Queue[_Attempt]",
        trace_id: Optional[str] = None,
    ) -> Optional[_Attempt]:
        r = self._pick(exclude)
        if r is None:
            return None
        a = _Attempt(r, reason)
        self.metrics.record_attempt(r.name, reason)
        tr = self.tracer
        traced = trace_id is not None and tr is not None and tr.enabled

        def call() -> Response:
            timeout_s = self.cfg.request_timeout_ms / 1000.0
            if not traced:
                # Untraced: exact pre-trace call shape, so fakes with the
                # old predict signature keep working.
                return r.client.predict(body, query, timeout_s, cancel=a.cancel)
            # One 16-hex span id per ATTEMPT: it rides the traceparent
            # header to the replica (whose serve_request records it as
            # remote_parent) AND is recorded on the attempt span as
            # span_hex — the two halves obs/merge.py joins on.
            attempt_hex = new_span_hex()
            with tr.bind(trace_id):
                with tr.span(
                    "router_attempt", replica=r.name, reason=reason,
                    span_hex=attempt_hex,
                ) as sp:
                    resp = r.client.predict(
                        body, query, timeout_s, cancel=a.cancel,
                        traceparent=format_traceparent(trace_id, attempt_hex),
                    )
                    sp.set(status=resp[0], cancelled=a.cancel.is_set())
                    return resp

        def run() -> None:
            ok: Optional[bool] = None
            try:
                resp = call()
                a.outcome = ("response", resp)
                ok = resp[0] < 500
                with self._lock:
                    r.ever_ok = True  # answered: warming grace is over
            except Exception as e:
                a.outcome = ("fail", e)
                ok = False
                if _is_conn_refused(e) and not r.ever_ok:
                    # Still warming (supervisor raced readiness, or a fake
                    # marked it ready early): neutral for the breaker —
                    # release the permit without recording an outcome —
                    # and out of rotation until a scrape succeeds.
                    ok = None
                    with self._lock:
                        if r.healthy:
                            self._log_event(
                                "replica_warming", replica=r.name,
                            )
                        r.healthy = False
            if ok is False and a.cancel.is_set():
                ok = None  # cancelled loser: neutral for the breaker
            self._finish_attempt(a, ok)
            done.put(a)

        a.thread = threading.Thread(
            target=run, name=f"router-attempt-{r.name}", daemon=True
        )
        a.thread.start()
        return a

    @staticmethod
    def _cancel(attempts: List[_Attempt], winner: Optional[_Attempt]) -> None:
        for a in attempts:
            if a is winner or a.outcome is not None:
                continue
            a.cancel.set()
            cancel_hook = getattr(a.replica.client, "cancel_attempt", None)
            if cancel_hook is not None:
                try:
                    cancel_hook(a.cancel)
                except Exception:
                    pass

    def _should_shed_batch(self) -> bool:
        """Bulk shedding rule: with ``batch_shed_queue_depth`` armed,
        ?priority=batch requests are shed when EVERY eligible replica's
        scraped interactive queue is at or past the threshold — bulk work
        must never consume the last admission the interactive tail needs.
        Interactive traffic is never shed by this rule."""
        threshold = int(self.cfg.batch_shed_queue_depth)
        if threshold <= 0:
            return False
        with self._lock:
            eligible = [
                r
                for r in self._replicas.values()
                if r.ready and not r.draining and r.healthy
                and r.breaker.available()
            ]
            if not eligible:
                return False  # the normal no-replica path answers this
            return all(
                r.queue_depth_interactive >= threshold for r in eligible
            )

    def dispatch(
        self, body: bytes, query: str = "",
        trace_context: Optional[Tuple[str, Optional[str]]] = None,
        info: Optional[dict] = None,
    ) -> Response:
        """Route one request; ALWAYS returns a response.  A 5xx here means
        every eligible replica (and every retry/hedge) failed — the
        client-visible failure the fleet soak requires to be zero.
        ``?priority=batch`` requests may additionally be SHED here (a
        policy 503, accounted separately from failures) when the fleet's
        interactive queues are saturated, and are never hedged — hedges
        are a p99-tail spend reserved for interactive traffic.

        ``trace_context`` is an optional (trace_id, parent span hex) pair
        parsed from an inbound traceparent header — an external client's
        trace continues through the fleet; without one a traced router
        mints a fresh request trace id.

        ``info``, when given, is filled in-place with attribution for
        the caller's response headers: ``cache_hit``, ``model_step``
        (the serving checkpoint step this answer came from), and
        ``lineage_id`` — every served prediction, including a cache
        hit, stays attributable to the exact training step."""
        priority = _priority_of(query)
        if priority == "batch" and self._should_shed_batch():
            self.metrics.record_batch_shed()
            self._log_event("batch_shed")
            return self._error(
                503, "bulk traffic shed: interactive queues saturated; "
                "retry with backoff"
            )
        t0 = time.monotonic()
        inf = info if info is not None else {}
        tr = self.tracer
        cache_key = None
        if self.cache.enabled and not _cache_bypass(query):
            ident = self._cache_identity()
            if ident is not None:
                cache_key = response_key(
                    body, ident[0], ident[1], lineage_id=ident[2]
                )
                cached = self.cache.get(cache_key)
                if cached is not None:
                    # A hit is a real answered request: it feeds the same
                    # ledgers (latency ring, SLO) as a routed one — the
                    # p99 win must be visible, not hidden from the stats.
                    latency_s = time.monotonic() - t0
                    self.metrics.record_request(latency_s, True)
                    self.slo.observe(priority, latency_s, True)
                    inf["cache_hit"] = True
                    inf["model_step"] = ident[0]
                    inf["lineage_id"] = ident[2]
                    if tr is not None and tr.enabled:
                        # The hit used to return without a span — a
                        # dangling trace with no fleet-side record.  The
                        # cache_hit span closes it, carrying the same
                        # lineage attribution as a routed answer, and is
                        # breaker-neutral by construction: no replica is
                        # touched, so no breaker sees this request.
                        trace_id, parent_hex = (
                            trace_context
                            if trace_context is not None
                            else (new_trace_id(), None)
                        )
                        with tr.bind(trace_id, parent_hex):
                            with tr.span(
                                "cache_hit",
                                priority=priority,
                                model_step=ident[0],
                                lineage_id=ident[2],
                            ) as sp:
                                sp.set(status=cached[0])
                    return cached
        if tr is not None and tr.enabled:
            trace_id, parent_hex = (
                trace_context
                if trace_context is not None
                else (new_trace_id(), None)
            )
            with tr.bind(trace_id, parent_hex):
                with tr.span("route_request", priority=priority) as sp:
                    status, ctype, payload = self._dispatch_inner(
                        body, query, priority, trace_id, info=inf
                    )
                    sp.set(
                        status=status,
                        model_step=inf.get("model_step"),
                        lineage_id=inf.get("lineage_id"),
                    )
        else:
            status, ctype, payload = self._dispatch_inner(
                body, query, priority, info=inf
            )
        ok = status < 500
        latency_s = time.monotonic() - t0
        self.metrics.record_request(latency_s, ok)
        self.slo.observe(priority, latency_s, ok)
        if cache_key is not None and ok:
            self.cache.put(cache_key, (status, ctype, payload))
        return status, ctype, payload

    # -- response cache -----------------------------------------------------

    def _cache_identity(self) -> Optional[Tuple[int, str, Optional[str]]]:
        """The fleet's consensus serving identity (step, quant mode,
        lineage id), or None when there isn't one — no scraped step yet,
        or mixed steps / quant modes mid-rolling-reload (caching simply
        pauses; the step is also in the key, so this is belt on top of
        braces).  The lineage id is part of the returned identity only
        when every live replica agrees on one; disagreement or the
        unknown marker degrades to None (the pre-lineage key), never a
        refusal to cache.  A consensus step DIFFERENT from the last one
        flushes the cache: that is the fleet-wide invalidation on any
        reload — forward or rollback — that changes the serving step."""
        flush = False
        with self._lock:
            live = [
                r for r in self._replicas.values()
                if r.ready and r.healthy and not r.draining
                and r.checkpoint_step is not None
            ]
            steps = {int(r.checkpoint_step) for r in live}
            quants = {r.quant_mode or "none" for r in live}
            if len(steps) != 1 or len(quants) != 1:
                return None
            step, quant = steps.pop(), quants.pop()
            lids = {r.lineage_id for r in live}
            lid = lids.pop() if len(lids) == 1 else None
            if lid == obs_lineage.LINEAGE_UNKNOWN:
                lid = None
            if self._cache_step is not None and self._cache_step != step:
                flush = True
            self._cache_step = step
        if flush:
            # Outside _lock: the router lock must never wait on the cache
            # lock while a put is evicting.
            dropped = self.cache.invalidate("step_change")
            self._log_event(
                "cache_invalidate", reason="step_change", dropped=dropped,
                step=step,
            )
        return step, quant, lid

    def invalidate_cache(self, reason: str) -> int:
        """Fleet-wide cache flush, called by the supervisor around any
        reload outcome that moves the serving step (including the
        rollback after an aborted one).  Always logged when the cache is
        on — the soak audits for this record on the rollback path."""
        if not self.cache.enabled:
            return 0
        dropped = self.cache.invalidate(reason)
        with self._lock:
            self._cache_step = None  # re-learn consensus from scrapes
        self._log_event("cache_invalidate", reason=reason, dropped=dropped)
        return dropped

    def _error(self, status: int, msg: str) -> Response:
        return status, "application/json", json.dumps({"error": msg}).encode()

    def _dispatch_inner(
        self, body: bytes, query: str, priority: str = "interactive",
        trace_id: Optional[str] = None, info: Optional[dict] = None,
    ) -> Response:
        cfg = self.cfg
        done: "queue.Queue[_Attempt]" = queue.Queue()
        attempts: List[_Attempt] = []
        tried: List[str] = []
        retries_left = max(0, int(cfg.retries))
        hedges_left = (
            max(0, int(cfg.hedge_max))
            if cfg.hedge_ms > 0 and priority == "interactive"
            else 0
        )

        a = self._launch_waiting(body, query, "primary", tried, done, trace_id)
        if a is None:
            self._log_event("no_replicas")
            return self._error(503, "no replicas available")
        attempts.append(a)
        tried.append(a.replica.name)
        pending = 1

        while True:
            timeout = cfg.hedge_ms / 1000.0 if hedges_left > 0 else None
            try:
                fin: _Attempt = done.get(timeout=timeout)
            except queue.Empty:
                # The tail case: nobody answered within hedge_ms — duplicate
                # to another replica, first answer wins.
                hedges_left -= 1
                h = self._launch(body, query, "hedge", tried, done, trace_id)
                if h is not None:
                    self.metrics.record_hedge()
                    attempts.append(h)
                    tried.append(h.replica.name)
                    pending += 1
                continue

            pending -= 1
            kind, val = fin.outcome  # type: ignore[misc]
            if kind == "response":
                st, ctype, payload = val[:3]  # type: ignore[misc]
                if st < 500:
                    # Success or a client-owned 4xx: either way the replica
                    # answered coherently — return it, cancel the rest
                    # (each loser's own thread does its bookkeeping).
                    self._cancel(attempts, fin)
                    if fin.reason == "hedge":
                        self.metrics.record_hedge_win()
                    if info is not None:
                        # Attribution: prefer the replica's per-response
                        # model-step header (exact even mid-reload) over
                        # the last scrape's step.
                        hdr = val[3] if len(val) > 3 else None
                        info["cache_hit"] = False
                        info["replica"] = fin.replica.name
                        if hdr is not None and hdr.isdigit():
                            info["model_step"] = int(hdr)
                        elif hdr is not None:
                            info["model_step"] = hdr
                        else:
                            info["model_step"] = fin.replica.checkpoint_step
                        info["lineage_id"] = fin.replica.lineage_id
                    return st, ctype, payload
                cause = f"http_{st}"
            else:
                cause = (
                    "cancelled" if fin.cancel.is_set() else "transport"
                )
            if fin.cancel.is_set():
                # A cancelled loser finishing late is not a new failure;
                # don't burn a retry on it.
                if pending == 0 and retries_left == 0:
                    return self._error(503, "all replica attempts failed")
                continue

            if retries_left > 0:
                retries_left -= 1
                self.metrics.record_retry(cause)
                # Full-jitter backoff before the retry (attempt number =
                # how many have failed so far).
                n_failed = len([x for x in attempts if x.outcome is not None])
                ceiling = min(
                    cfg.retry_backoff_ms * (2.0 ** max(n_failed - 1, 0)),
                    1000.0,
                ) / 1000.0
                delay = self._rng.uniform(0.0, ceiling)
                if delay > 0:
                    self._sleep(delay)
                nxt = self._launch(body, query, "retry", tried, done, trace_id)
                if nxt is None and pending == 0:
                    # With nothing pending this would fall through to an
                    # instant 503 — the same transient zero-eligible
                    # window the admission wait rides out (an untried
                    # replica readmitting mid-reload); wait for it too.
                    nxt = self._launch_waiting(
                        body, query, "retry", tried, done, trace_id
                    )
                if nxt is not None:
                    attempts.append(nxt)
                    tried.append(nxt.replica.name)
                    pending += 1
                    continue
                # Nowhere to retry: fall through to waiting on any
                # still-pending attempt, else fail.
            if pending > 0:
                continue
            self._log_event(
                "request_failed", attempts=len(attempts), last_cause=cause
            )
            return self._error(503, "all replica attempts failed")

    # -- fleet health summary ----------------------------------------------

    def healthz(self) -> dict:
        statuses = self.replica_status()
        ready = [
            s
            for s in statuses
            if s["ready"] and not s["draining"] and s["healthy"]
        ]
        out = {
            "status": "ok" if ready else "unavailable",
            "replicas": len(statuses),
            "ready": len(ready),
            "checkpoint_steps": sorted(
                {
                    s["checkpoint_step"]
                    for s in statuses
                    if s["checkpoint_step"] is not None
                }
            ),
            "replica_status": statuses,
        }
        steps = out["checkpoint_steps"]
        # Nonzero only in a mixed-weights window (mid-rolling-reload);
        # the fleet test pins >0 there and ==0 once converged.
        out["step_skew"] = (max(steps) - min(steps)) if steps else None
        if self.cache.enabled:
            out["cache"] = self.cache.stats()
        if self.slo.enabled:
            # Error budgets + burn rates on the fleet's ONE health
            # endpoint (ISSUE 14 tentpole: the SLO layer is scrapeable
            # where the operator already looks).
            out["slo"] = self.slo.status()
            out["slo_alerts"] = [
                a for a in self.health.alerts
                if str(a.get("alert", "")).startswith("slo_")
            ]
        return out
