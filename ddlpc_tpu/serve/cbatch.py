"""Continuous batching: refill the device pipeline as slots free.

PR 1's :class:`~ddlpc_tpu.serve.batching.MicroBatcher` is
coalesce-and-wait: ONE worker takes a batch (waiting up to
``max_wait_ms`` for it to fill), runs the forward to completion, and only
then looks at the queue again.  Two structural costs under ragged
traffic (the gap flagged at the engine's jit-cache counters):

- every lightly-loaded request pays the coalescing wait — a timer, not
  work — before its forward is even dispatched;
- while a forward executes, the queue builds but nothing is dispatched:
  the host-side stitch/np conversion tail of batch N serializes with the
  device work of batch N+1.

:class:`ContinuousBatcher` replaces the timer with *slots*: ``slots``
worker threads each assemble-and-dispatch whatever is queued (up to
``max_batch``, padded by the engine to the power-of-two bucket) the
moment they are free.  There is no coalescing wait at all — batching
emerges from concurrency: while every slot is busy, arrivals accumulate
and the next freed slot takes them as one batch.  Under light load a
request's forward dispatches immediately (batch of 1, the smallest
bucket); under saturation batches fill to ``max_batch`` with zero timer
latency.  A freed slot REFILLS from the queue without draining anything
— the continuous-batching admission loop of the TPU serving literature
(PAPERS.md: Gemma-on-TPU serving), applied to fixed-size tile requests.

Priority classes
----------------

Every payload carries a class: ``interactive`` (latency-sensitive scene
requests) or ``batch`` (bulk tiling work that wants throughput, not p99).
Each class has its own bounded admission queue — bulk work queues deeply
(``batch_queue_limit``) without consuming interactive admission, and
sheds independently.  Assembly order is interactive-first with a
starvation bound: every ``starvation_every``-th assembly seats at least
one batch-class item first, so an interactive flood cannot starve bulk
work forever (the bound is test-pinned).

The typed error contract, deadlines, drain semantics, and the
``forward``/``Future`` API are exactly the MicroBatcher's, so the
frontend swaps one for the other on a config knob
(``ServeConfig.batcher``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from contextlib import nullcontext
from typing import Callable, Dict, List, Optional, Sequence

from ddlpc_tpu.analysis import lockcheck
from ddlpc_tpu.serve.batching import (
    DeadlineExceeded,
    EngineClosed,
    Overloaded,
    _fail,
)

_NULL_CTX = nullcontext()

PRIORITIES = ("interactive", "batch")


def check_priority(priority: str) -> str:
    if priority not in PRIORITIES:
        raise ValueError(
            f"unknown priority class {priority!r} "
            f"(expected one of {PRIORITIES})"
        )
    return priority


class _Item:
    __slots__ = (
        "payload", "future", "enqueued", "deadline", "t_trace", "refill",
        "trace_id",
    )

    def __init__(
        self,
        payload,
        deadline: Optional[float],
        now: float,
        t_trace: float = 0.0,
        refill: bool = False,
        trace_id: Optional[str] = None,
    ):
        self.payload = payload
        self.future: Future = Future()
        self.enqueued = now
        self.deadline = deadline
        self.t_trace = t_trace
        # Request trace id captured on the SUBMITTING thread (the one
        # holding the tracer binding) — batch spans execute on a worker
        # thread and name every request trace they served via this.
        self.trace_id = trace_id
        # True when this item arrived while a forward was executing: the
        # assembly that takes it is a pipeline REFILL (work admitted
        # without waiting for the previous batch's world to drain) — the
        # property the continuous-batching tests pin.
        self.refill = refill


@lockcheck.guarded
class ContinuousBatcher:
    """Slot-based continuous batcher with priority classes.

    ``forward(list_of_payloads) -> sequence_of_results`` runs on a slot
    thread; it must be thread-safe for ``slots > 1`` (the engine's
    ``forward_windows`` is — state snapshot + locked jit cache).

    Shared state is guarded by ``_cond`` (``# guarded-by:`` annotations
    enforced under ``DDLPC_LOCKCHECK=1`` — docs/ANALYSIS.md).
    """

    def __init__(
        self,
        forward: Callable[[List], Sequence],
        max_batch: int = 8,
        queue_limit: int = 64,
        batch_queue_limit: int = 256,
        slots: int = 2,
        starvation_every: int = 4,
        metrics=None,
        tracer=None,
        start: bool = True,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        if batch_queue_limit < 1:
            raise ValueError(
                f"batch_queue_limit must be >= 1, got {batch_queue_limit}"
            )
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self._forward = forward
        self.max_batch = int(max_batch)
        self.queue_limit = int(queue_limit)
        self.batch_queue_limit = int(batch_queue_limit)
        self.slots = int(slots)
        self.starvation_every = max(1, int(starvation_every))
        self.metrics = metrics
        self.tracer = tracer
        self._cond = lockcheck.condition("ContinuousBatcher._cond")
        self._queues: Dict[str, deque] = {  # guarded-by: _cond
            "interactive": deque(),
            "batch": deque(),
        }
        self._closing = False  # guarded-by: _cond
        self._busy = 0  # slots currently inside forward  # guarded-by: _cond
        self._assemblies = 0  # guarded-by: _cond
        # batched forward calls issued (read cross-thread by tests/
        # metrics/the frontend's profiler — locked like the queue)
        self.forward_count = 0  # guarded-by: _cond
        # assemblies that seated at least one item enqueued while a
        # forward was in flight: the pipeline stayed hot instead of
        # draining (the continuous-batching property, test-pinned)
        self.refills = 0  # guarded-by: _cond
        # Per-slot utilization accounting (ISSUE 14 satellite): cumulative
        # busy seconds per slot + the in-flight forward's start, read out
        # windowed by slot_busy_fractions() so sizing `slots` stops being
        # guesswork (published as ddlpc_serve_slot_busy_fraction{slot}).
        now0 = time.monotonic()
        self._slot_busy_s = [0.0] * self.slots  # guarded-by: _cond
        self._slot_t0: List[Optional[float]] = (
            [None] * self.slots
        )  # guarded-by: _cond
        self._slot_mark = [(now0, 0.0)] * self.slots  # guarded-by: _cond
        self._threads: List[threading.Thread] = []
        self._started = False
        if start:
            self.start()

    # ---- admission ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self.slots):
            t = threading.Thread(
                target=self._run, args=(i,), name=f"serve-cbatch-{i}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def submit(
        self,
        payload,
        deadline_ms: Optional[float] = None,
        priority: str = "interactive",
    ) -> Future:
        """Enqueue one payload; raises typed :class:`Overloaded` /
        :class:`EngineClosed` instead of blocking, like the MicroBatcher."""
        return self.submit_many(
            [payload], deadline_ms=deadline_ms, priority=priority
        )[0]

    def submit_many(
        self,
        payloads: Sequence,
        deadline_ms: Optional[float] = None,
        priority: str = "interactive",
    ) -> List[Future]:
        """All-or-nothing admission into one priority class's queue."""
        check_priority(priority)
        if not payloads:
            return []
        now = time.monotonic()
        deadline = None if not deadline_ms else now + deadline_ms / 1000.0
        limit = (
            self.queue_limit
            if priority == "interactive"
            else self.batch_queue_limit
        )
        with self._cond:
            if self._closing:
                raise EngineClosed("batcher is draining; not accepting work")
            q = self._queues[priority]
            if len(q) + len(payloads) > limit:
                if self.metrics is not None:
                    self.metrics.record_shed(len(payloads), priority=priority)
                raise Overloaded(
                    f"{priority} queue full ({len(q)}/{limit} + "
                    f"{len(payloads)} new); retry with backoff"
                )
            t_trace = 0.0
            trace_id = None
            if self.tracer is not None and self.tracer.enabled:
                t_trace = self.tracer.now()
                trace_id = self.tracer.current_trace_id()
            refill = self._busy > 0
            items = [
                _Item(p, deadline, now, t_trace, refill, trace_id)
                for p in payloads
            ]
            q.extend(items)
            self._publish_depths_locked()
            self._cond.notify_all()
        return [it.future for it in items]

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return sum(len(q) for q in self._queues.values())

    def queue_depths(self) -> Dict[str, int]:
        """Per-priority-class queue depths — what ``/healthz`` carries so
        the router's one-scrape contract covers priority-aware dispatch."""
        with self._cond:
            return {p: len(q) for p, q in self._queues.items()}

    def _publish_depths_locked(self) -> None:
        if self.metrics is None:
            return
        depths = {p: len(q) for p, q in self._queues.items()}
        self.metrics.set_queue_depth(sum(depths.values()))
        set_prio = getattr(self.metrics, "set_priority_queue_depth", None)
        if set_prio is not None:
            set_prio(depths)

    # ---- slot workers ------------------------------------------------------

    def _assemble_locked(self) -> List[_Item]:
        """Take up to ``max_batch`` items: interactive first, then batch —
        except every ``starvation_every``-th assembly, which seats one
        batch-class item FIRST (the starvation bound)."""
        self._assemblies += 1
        order = ["interactive", "batch"]
        batch: List[_Item] = []
        if (
            self._assemblies % self.starvation_every == 0
            and self._queues["batch"]
        ):
            batch.append(self._queues["batch"].popleft())
        for p in order:
            q = self._queues[p]
            while q and len(batch) < self.max_batch:
                batch.append(q.popleft())
        return batch

    def _take_batch(self) -> Optional[List[_Item]]:
        """Block until work exists (then take it IMMEDIATELY — no
        coalescing timer; batching emerges from busy slots) or the
        batcher is closed and drained (None)."""
        with self._cond:
            while not self._closing and not any(
                self._queues[p] for p in PRIORITIES
            ):
                self._cond.wait(0.05)
            if not any(self._queues[p] for p in PRIORITIES):
                return None  # closing and drained
            batch = self._assemble_locked()
            if any(it.refill for it in batch):
                self.refills += 1
            self._busy += 1
            self._publish_depths_locked()
            return batch

    def _run(self, slot: int) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            t0 = time.monotonic()
            with self._cond:
                self._slot_t0[slot] = t0
            try:
                self._execute(batch)
            finally:
                with self._cond:
                    self._busy -= 1
                    self._slot_busy_s[slot] += time.monotonic() - t0
                    self._slot_t0[slot] = None

    def slot_busy_fractions(self) -> Dict[int, float]:
        """Per-slot busy fraction since the PREVIOUS readout (an in-flight
        forward counts up to now).  The caller's cadence defines the
        window — the frontend's metrics emitter reads this every
        ``metrics_every_s`` and publishes
        ``ddlpc_serve_slot_busy_fraction{slot}``."""
        now = time.monotonic()
        out: Dict[int, float] = {}
        with self._cond:
            for i in range(self.slots):
                busy = self._slot_busy_s[i]
                if self._slot_t0[i] is not None:
                    busy += now - self._slot_t0[i]
                last_t, last_busy = self._slot_mark[i]
                dt = max(now - last_t, 1e-9)
                out[i] = min(max((busy - last_busy) / dt, 0.0), 1.0)
                self._slot_mark[i] = (now, busy)
        return out

    def _execute(self, batch: List[_Item]) -> None:
        now = time.monotonic()
        live: List[_Item] = []
        for it in batch:
            if it.deadline is not None and now > it.deadline:
                if self.metrics is not None:
                    self.metrics.record_deadline()
                _fail(
                    it.future,
                    DeadlineExceeded(
                        f"queued {now - it.enqueued:.3f}s, past deadline"
                    ),
                )
            elif not it.future.set_running_or_notify_cancel():
                continue  # client cancelled while queued
            else:
                live.append(it)
        if not live:
            return
        with self._cond:
            self.forward_count += 1
        tracer = self.tracer
        # The request trace ids this batch serves (flat list of scalars —
        # schema-legal): how obs/merge.py attributes worker-thread batch
        # spans to the cross-process request timelines they belong to.
        tids = sorted({it.trace_id for it in live if it.trace_id})
        if tracer is not None and tracer.enabled:
            tracer.add_span(
                "batch_coalesce",
                live[0].t_trace,
                tracer.now(),
                batch=len(live),
                **({"trace_ids": tids} if tids else {}),
            )
        span = (
            tracer.span(
                "jit_execute", batch=len(live),
                **({"trace_ids": tids} if tids else {}),
            )
            if tracer is not None
            else _NULL_CTX
        )
        try:
            with span:
                results = list(self._forward([it.payload for it in live]))
            if len(results) != len(live):
                raise RuntimeError(
                    f"forward returned {len(results)} results for "
                    f"{len(live)} payloads"
                )
        except Exception as e:  # fail the batch, keep serving
            for it in live:
                _fail(it.future, e)
            return
        for it, res in zip(live, results):
            it.future.set_result(res)
        if self.metrics is not None:
            self.metrics.record_batch(len(live), self.max_batch)

    # ---- shutdown ----------------------------------------------------------

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop admission; drain (default) or abandon the queues; join."""
        if drain and not self._started:
            self.start()  # a deferred-start batcher still owes a drain
        with self._cond:
            self._closing = True
            if not drain:
                for q in self._queues.values():
                    while q:
                        _fail(
                            q.popleft().future,
                            EngineClosed("batcher closed without drain"),
                        )
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout)

    def __enter__(self) -> "ContinuousBatcher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)
