"""Replica supervision + zero-downtime rolling reload: the fleet's
process tier (ISSUE 10 tentpole).

``ReplicaSupervisor`` launches and babysits N serving replicas, each a
``python -m ddlpc_tpu.serve.server`` subprocess on an ephemeral port
(learned through a ``--port-file``), and keeps the routing tier
(serve/router.py) in sync with reality:

- **launch → warmup → register**: a replica only enters dispatch after
  its port file lands (written post-``engine.warmup()``) and ``/healthz``
  answers ``ok`` — first traffic never pays a compile;
- **exit classification + restart** via the SAME machinery as the
  training supervisor (resilience/supervisor.py): ``classify_exit`` on
  the exit status, :class:`RestartPolicy` for full-jitter backoff,
  crash-loop give-up, and the restart budget.  "Progress" for a serving
  replica means it became ready since launch — a replica that dies warm
  relaunches immediately, one that crash-loops at import backs off and
  eventually gives up LOUDLY while the rest of the fleet keeps serving;
- **graceful replacement**: ``stop()`` SIGTERMs every replica, which runs
  server.py's drain path (finish in-flight, flush metrics, exit 0);
- **rolling hot-reload**: push a new checkpoint replica-by-replica —
  router drain → ``POST /reload`` → warmup confirm → readmit — so a
  training run updates a live fleet with zero dropped requests.  If any
  replica's reload errors or quarantines the blob, the WHOLE fleet is
  rolled back to the old step (explicit ``step=`` reloads) and the update
  reports aborted.

Like the router, this module is deliberately jax-free: only the replica
subprocesses pay the jax import.

CLI::

    python -m ddlpc_tpu.serve.fleet --config configs/fleet_vaihingen.json
    python -m ddlpc_tpu.serve.fleet --workdir runs/x --replicas 3 --port 8570
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Callable, Dict, List, Optional
from urllib.parse import urlparse

from ddlpc_tpu.config import FleetConfig
from ddlpc_tpu.obs import lineage as obs_lineage
from ddlpc_tpu.obs.aggregate import TelemetryAggregator
from ddlpc_tpu.obs.http import PROMETHEUS_CTYPE, render_metrics, wants_prometheus
from ddlpc_tpu.obs.registry import MetricsRegistry
from ddlpc_tpu.obs.tracing import Tracer, parse_traceparent
from ddlpc_tpu.resilience.supervisor import RestartPolicy, classify_exit
from ddlpc_tpu.serve.router import FleetRouter, HTTPReplicaClient
from ddlpc_tpu.serve.server import ServeHTTPServer


class _ManagedReplica:
    """One supervised replica: process handle + restart policy state."""

    def __init__(self, idx: int, home: str, cfg: FleetConfig):
        self.idx = idx
        self.name = f"r{idx}"
        self.home = home  # <fleet_dir>/r<idx>: config, port file, log, metrics
        self.cfg_path = os.path.join(home, "serve.json")
        self.port_file = os.path.join(home, "port")
        self.log_path = os.path.join(home, "replica.log")
        self.policy = RestartPolicy(
            max_restarts=cfg.max_restarts,
            crash_loop_limit=cfg.crash_loop_limit,
            backoff_base_s=cfg.backoff_base_s,
            backoff_cap_s=cfg.backoff_cap_s,
        )
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.client: Optional[HTTPReplicaClient] = None
        self.launches = 0
        self.became_ready = False  # since the most recent launch
        self.gave_up = False
        # Scale-down marker: a retired replica's supervision loop exits
        # instead of relaunching when its process dies — the orderly
        # counterpart of gave_up (serve/autoscale.py drives it).
        self.retired = False
        self.ready_evt = threading.Event()


class ReplicaSupervisor:
    """Launch, watch, classify, back off, relaunch — per serving replica.

    ``env_fn(replica_idx, launch_n) -> dict | None`` varies a replica's
    environment per launch (how the fleet soak injects a different
    ``DDLPC_CHAOS`` schedule into each replica / each restart).
    """

    def __init__(
        self,
        cfg: FleetConfig,
        router: Optional[FleetRouter] = None,
        registry: Optional[MetricsRegistry] = None,
        logger=None,
        env_fn: Optional[Callable[[int, int], Optional[dict]]] = None,
        echo: bool = True,
        aggregator: Optional[TelemetryAggregator] = None,
    ):
        self.cfg = cfg
        self.fleet_dir = cfg.resolved_fleet_dir()
        if registry is None:
            registry = router.registry if router is not None else MetricsRegistry()
        self.registry = registry
        self.router = (
            router
            if router is not None
            else FleetRouter(cfg, registry=registry, logger=logger)
        )
        # Fleet telemetry aggregation (obs/aggregate.py): replicas opt in
        # as metrics sources exactly when they enter dispatch, and leave
        # when their process dies — the aggregator's staleness flag covers
        # the gap in between.
        self.aggregator = aggregator
        self.logger = logger
        self.env_fn = env_fn
        self.echo = echo
        self._restarts = registry.counter(
            "ddlpc_fleet_restarts_total",
            "Replica relaunches, by replica and classified exit cause.",
            labelnames=("replica", "cause"),
        )
        # Deploy latency: checkpoint durable on disk (lineage saved_at,
        # stamped at the durable-write moment) → 100% of the fleet
        # serving that step.  Set once per completed rolling reload;
        # stays at the last value between reloads.
        self._deploy_latency = registry.gauge(
            "ddlpc_deploy_latency_s",
            "Seconds from checkpoint durable-write to the whole fleet "
            "serving it, per completed rolling reload.",
        )
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._reload_lock = threading.Lock()
        # replicas/_threads grow at runtime (scale_up) — every iteration
        # works on a snapshot taken under this lock.
        self._replicas_lock = threading.Lock()
        self.replicas: List[_ManagedReplica] = []
        for i in range(cfg.replicas):
            home = os.path.join(self.fleet_dir, f"r{i}")
            self.replicas.append(_ManagedReplica(i, home, cfg))
        self._next_idx = cfg.replicas  # next scale-up replica index

    def _snapshot(self) -> List[_ManagedReplica]:
        with self._replicas_lock:
            return list(self.replicas)

    def _spawn_supervision(self, rp: _ManagedReplica) -> None:
        """Config on disk + one supervision thread — shared by boot-time
        start() and runtime scale_up()."""
        self._write_serve_config(rp)
        t = threading.Thread(
            target=self._run_replica, args=(rp,),
            name=f"fleet-{rp.name}", daemon=True,
        )
        with self._replicas_lock:
            self._threads.append(t)
        t.start()

    # -- plumbing -----------------------------------------------------------

    def _say(self, msg: str) -> None:
        if self.echo:
            print(f"[fleet] {msg}", file=sys.stderr, flush=True)

    def _log(self, event: str, **fields) -> None:
        """Flat kind="fleet" records on the router.jsonl stream."""
        if self.logger is None:
            return
        try:
            self.logger.log(
                {"kind": "fleet", "event": event, **fields}, echo=False
            )
        except Exception:
            pass

    # -- launch / readiness -------------------------------------------------

    def _write_serve_config(self, rp: _ManagedReplica) -> None:
        os.makedirs(rp.home, exist_ok=True)
        serve_cfg = self.cfg.replica_serve_config(metrics_dir=rp.home)
        with open(rp.cfg_path, "w") as f:
            f.write(serve_cfg.to_json())

    def _launch(self, rp: _ManagedReplica) -> None:
        rp.launches += 1
        rp.became_ready = False
        rp.port = None
        try:
            os.unlink(rp.port_file)
        except OSError:
            pass
        env = None
        if self.env_fn is not None:
            env = self.env_fn(rp.idx, rp.launches)
        if env is None:
            env = dict(os.environ)
        # The replica must import ddlpc_tpu from the same tree as the
        # supervisor regardless of the caller's cwd.
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [
            sys.executable,
            "-m",
            "ddlpc_tpu.serve.server",
            "--config",
            rp.cfg_path,
            "--port-file",
            rp.port_file,
        ]
        log = open(rp.log_path, "ab")
        try:
            rp.proc = subprocess.Popen(
                cmd, env=env, stdout=log, stderr=subprocess.STDOUT
            )
        finally:
            log.close()
        self._say(f"{rp.name}: launched pid {rp.proc.pid} (launch {rp.launches})")
        self._log(
            "replica_launch", replica=rp.name, pid=rp.proc.pid,
            launch=rp.launches,
        )

    def _wait_ready(self, rp: _ManagedReplica) -> bool:
        """Port file lands (post-warmup) and /healthz answers ok."""
        deadline = time.monotonic() + self.cfg.warmup_timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            if rp.proc is None or rp.proc.poll() is not None:
                return False  # died during startup
            if rp.port is None and os.path.exists(rp.port_file):
                try:
                    with open(rp.port_file) as f:
                        rp.port = int(f.read().strip())
                    rp.client = HTTPReplicaClient(
                        rp.name, self.cfg.host, rp.port
                    )
                except (OSError, ValueError):
                    rp.port = None
            if rp.client is not None and rp.port is not None:
                try:
                    h = rp.client.healthz(self.cfg.scrape_timeout_s)
                    if h.get("status") == "ok":
                        return True
                except Exception:
                    pass
            time.sleep(0.2)
        return False

    # -- the per-replica supervision loop ------------------------------------

    def _run_replica(self, rp: _ManagedReplica) -> None:
        while not self._stop.is_set() and not rp.retired:
            self._launch(rp)
            if self._wait_ready(rp) and not self._stop.is_set():
                rp.became_ready = True
                self.router.add_replica(rp.name, rp.client)
                if self.aggregator is not None:
                    client = rp.client
                    timeout_s = self.cfg.scrape_timeout_s
                    self.aggregator.add_source(
                        rp.name,
                        lambda c=client, t=timeout_s: c.metrics_text(t),
                    )
                self._say(f"{rp.name}: ready on port {rp.port}")
                self._log(
                    "replica_ready", replica=rp.name, port=rp.port,
                    launch=rp.launches,
                )
                rp.ready_evt.set()
            elif rp.proc is not None and rp.proc.poll() is None:
                # Alive but never became ready inside the warmup window:
                # a wedged start is a failed launch, not a serving replica.
                self._say(f"{rp.name}: warmup timeout — killing")
                try:
                    rp.proc.kill()
                except OSError:
                    pass
            rc = rp.proc.wait() if rp.proc is not None else -1
            self.router.remove_replica(rp.name)
            if self.aggregator is not None:
                self.aggregator.remove_source(rp.name)
            rp.ready_evt.clear()
            cause = classify_exit(rc)
            self._say(f"{rp.name}: exit {rc} ({cause})")
            self._log(
                "replica_exit", replica=rp.name, rc=rc, cause=cause,
                was_ready=rp.became_ready,
            )
            if self._stop.is_set():
                return
            if rp.retired:
                # Scale-down completing: the exit was ordered, not a
                # failure — no restart accounting, no relaunch.
                self._say(f"{rp.name}: retired (scale-down)")
                self._log("replica_retired", replica=rp.name)
                return
            self._restarts.inc(replica=rp.name, cause=cause)
            decision = rp.policy.record_exit(progressed=rp.became_ready)
            if decision != "restart":
                rp.gave_up = True
                msg = (
                    f"{rp.name}: giving up after {rp.policy.attempts} exits "
                    f"({decision}); the rest of the fleet keeps serving"
                )
                self._say(msg)
                self._log(
                    "replica_give_up", severity="critical", replica=rp.name,
                    attempts=rp.policy.attempts, reason=decision,
                )
                return
            delay = rp.policy.delay_s()
            if delay > 0:
                self._say(f"{rp.name}: backing off {delay:.2f}s before relaunch")
                self._stop.wait(delay)

    # -- lifecycle -----------------------------------------------------------

    def start(self, wait_ready: bool = True) -> int:
        """Launch every replica (each on its own supervision thread).
        With ``wait_ready`` blocks until each is ready or its warmup
        window expired; returns how many are ready."""
        os.makedirs(self.fleet_dir, exist_ok=True)
        initial = self._snapshot()
        for rp in initial:
            self._spawn_supervision(rp)
        self.router.start()
        if not wait_ready:
            return 0
        n = 0
        for rp in initial:
            if rp.ready_evt.wait(self.cfg.warmup_timeout_s):
                n += 1
        return n

    def ready_count(self) -> int:
        return sum(1 for rp in self._snapshot() if rp.ready_evt.is_set())

    # -- runtime elasticity (serve/autoscale.py drives these) ----------------

    def replica_count(self) -> int:
        """Replicas the fleet is steering toward: live or relaunching,
        excluding retired and given-up ones."""
        return sum(
            1 for rp in self._snapshot() if not rp.retired and not rp.gave_up
        )

    def scale_up(self) -> str:
        """Add one replica at runtime; returns its name immediately.
        Scale-up races warmup by design: the new replica enters dispatch
        through the SAME port-file + /healthz readiness gate as a boot
        launch, so first traffic never pays its compile."""
        with self._replicas_lock:
            idx = self._next_idx
            self._next_idx += 1
            home = os.path.join(self.fleet_dir, f"r{idx}")
            rp = _ManagedReplica(idx, home, self.cfg)
            self.replicas.append(rp)
        os.makedirs(self.fleet_dir, exist_ok=True)
        self._spawn_supervision(rp)
        self._log("scale_up_launch", replica=rp.name)
        return rp.name

    def scale_down(self, name: str) -> bool:
        """Retire one replica at runtime: drain its in-flight work, then
        SIGTERM (the replica's own drain path finishes the rest).  The
        supervision loop sees ``retired`` and exits instead of
        relaunching.  Returns False if ``name`` isn't a live candidate."""
        with self._replicas_lock:
            rp = next(
                (
                    r for r in self.replicas
                    if r.name == name and not r.retired and not r.gave_up
                ),
                None,
            )
        if rp is None:
            return False
        rp.retired = True
        self.router.drain(name, self.cfg.drain_timeout_s)
        if rp.proc is not None and rp.proc.poll() is None:
            try:
                rp.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        self._log("scale_down_retire", replica=name)
        return True

    def stop(self, grace_s: float = 30.0) -> None:
        """Graceful fleet shutdown: SIGTERM every replica (each drains —
        finish in-flight, flush metrics, exit 0), SIGKILL stragglers."""
        self._stop.set()
        live = self._snapshot()
        for rp in live:
            if rp.proc is not None and rp.proc.poll() is None:
                try:
                    rp.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + grace_s
        for rp in live:
            if rp.proc is None:
                continue
            remaining = max(deadline - time.monotonic(), 0.1)
            try:
                rp.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                self._say(f"{rp.name}: did not drain in {grace_s}s — SIGKILL")
                try:
                    rp.proc.kill()
                    rp.proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        with self._replicas_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=10)
        self.router.close()

    # -- rolling hot-reload ---------------------------------------------------

    def rolling_reload(
        self, step: Optional[int] = None, workdir: Optional[str] = None
    ) -> dict:
        """Push a checkpoint into the live fleet replica-by-replica:
        drain → /reload → warmup confirm → readmit.  Zero dropped
        requests: a draining replica finishes its in-flight work while
        the others keep serving.

        Fleet-wide fallback: if ANY replica's reload errors or
        quarantines the blob (the reader fell back past a corrupt
        checkpoint — train/checkpoint.py), every already-updated replica
        is reloaded back to the old step and the update reports
        ``{"ok": False, ...}`` — a fleet never serves mixed weights
        because one copy of the new blob was bad."""
        with self._reload_lock:
            return self._rolling_reload_locked(step, workdir)

    def _reload_payload(self, step, workdir) -> dict:
        payload: Dict[str, object] = {}
        if step is not None:
            payload["step"] = int(step)
        if workdir is not None:
            payload["workdir"] = workdir
        return payload

    def _reload_to(self, rp: _ManagedReplica, step: Optional[int]) -> bool:
        """Best-effort direct reload (rollback path): the engine's hot
        swap is atomic, so no drain is needed to go BACK to weights every
        in-flight request may already be using."""
        if rp.client is None:
            return False
        try:
            status, meta = rp.client.reload(
                self._reload_payload(step, None), self.cfg.scrape_timeout_s + 30
            )
            return status == 200 and "error" not in meta
        except Exception as e:
            self._log(
                "rollback_failed", replica=rp.name, severity="critical",
                error=f"{type(e).__name__}: {e}",
            )
            return False

    def _rolling_reload_locked(self, step, workdir) -> dict:
        live = [
            rp
            for rp in self._snapshot()
            if rp.ready_evt.is_set() and rp.client is not None
            and not rp.retired
        ]
        if not live:
            return {"ok": False, "error": "no ready replicas"}
        # The fleet-wide fallback target: what the fleet serves NOW.
        old_steps = []
        for rp in live:
            try:
                h = rp.client.healthz(self.cfg.scrape_timeout_s)
                if h.get("checkpoint_step") is not None:
                    old_steps.append(int(h["checkpoint_step"]))
            except Exception:
                pass
        old_step = max(old_steps) if old_steps else None
        self._log(
            "rolling_reload_start", step=step, old_step=old_step,
            replicas=len(live),
        )
        updated: List[_ManagedReplica] = []
        details = []
        new_step = None
        new_lineage: dict = {}
        for rp in live:
            self.router.drain(rp.name, self.cfg.drain_timeout_s)
            try:
                status, meta = rp.client.reload(
                    self._reload_payload(step, workdir),
                    self.cfg.scrape_timeout_s + 60,
                )
            except Exception as e:
                status, meta = 0, {"error": f"{type(e).__name__}: {e}"}
            quarantined = meta.get("quarantined_steps")
            ok = status == 200 and "error" not in meta and not quarantined
            details.append(
                {
                    "replica": rp.name,
                    "status": status,
                    "step": meta.get("step"),
                    "quarantined_steps": quarantined,
                    "error": meta.get("error"),
                }
            )
            if not ok:
                reason = (
                    f"quarantined {quarantined}"
                    if quarantined
                    else str(meta.get("error") or f"http {status}")
                )
                self._say(
                    f"rolling reload ABORTED on {rp.name}: {reason}; "
                    f"rolling fleet back to step {old_step}"
                )
                # Fleet-wide fallback.  The failing replica may already be
                # serving fallback weights (the reader's quarantine path) —
                # an explicit step= reload pins it to the same old step as
                # everyone else.
                rollback_ok = [self._reload_to(rp, old_step)]
                self.router.readmit(rp.name)
                for u in updated:
                    rollback_ok.append(self._reload_to(u, old_step))
                # Replicas that already served the new step are back on
                # the old one: anything cached against EITHER step is
                # suspect until the fleet re-converges — flush.
                self.router.invalidate_cache("reload_rollback")
                self.router.metrics.record_reload(ok=False)
                self._log(
                    "rolling_reload_aborted", replica=rp.name, reason=reason,
                    rolled_back_to=old_step,
                    rollback_clean=all(rollback_ok),
                )
                return {
                    "ok": False,
                    "aborted_on": rp.name,
                    "reason": reason,
                    "rolled_back_to": old_step,
                    "rollback_clean": all(rollback_ok),
                    "replicas": details,
                }
            new_step = meta.get("step")
            if isinstance(meta.get("lineage"), dict):
                new_lineage = meta["lineage"]
            # Warmup confirm: the replica answers /healthz with the new
            # step before it re-enters dispatch.
            confirm_deadline = time.monotonic() + self.cfg.scrape_timeout_s + 10
            while time.monotonic() < confirm_deadline:
                try:
                    h = rp.client.healthz(self.cfg.scrape_timeout_s)
                    if (
                        h.get("status") == "ok"
                        and h.get("checkpoint_step") == new_step
                    ):
                        break
                except Exception:
                    pass
                time.sleep(0.1)
            self.router.readmit(rp.name)
            updated.append(rp)
        # The serving step moved: every cached response answered for the
        # old weights.  (The router's consensus watcher would also catch
        # this on the next scrape; the explicit call makes the flush
        # synchronous with the reload result.)
        if new_step != old_step:
            self.router.invalidate_cache("rolling_reload")
        self.router.metrics.record_reload(ok=True)
        # Deploy latency: the last replica just confirmed the new step,
        # so the WHOLE fleet serves it now; anchor on the checkpoint's
        # durable-write stamp.  Pre-lineage checkpoints (v1/v2) have no
        # stamp — report the explicit unknown marker, never a fake zero.
        lineage_id = new_lineage.get("lineage_id")
        saved_at = new_lineage.get("saved_at")
        deploy_latency_s = None
        if isinstance(saved_at, (int, float)) and not isinstance(
            saved_at, bool
        ):
            deploy_latency_s = max(0.0, time.time() - float(saved_at))
            self._deploy_latency.set(deploy_latency_s)
        self._log(
            "rolling_reload_done", step=new_step, old_step=old_step,
            replicas=len(updated),
            lineage_id=lineage_id or obs_lineage.LINEAGE_UNKNOWN,
            deploy_latency_s=deploy_latency_s,
        )
        if self.logger is not None:
            # The fleet-side lineage event: joined with the trainer's
            # checkpoint_saved record (same lineage_id) by obs/merge.py
            # to place train→serve hand-off on one timeline.
            try:
                self.logger.log(
                    {
                        "kind": "lineage",
                        "event": "fleet_serving",
                        **obs_lineage.flatten(
                            new_lineage or obs_lineage.unknown_lineage(
                                new_step
                            )
                        ),
                        "deploy_latency_s": deploy_latency_s,
                        "replicas": len(updated),
                    },
                    echo=False,
                )
            except Exception:
                pass
        return {
            "ok": True,
            "step": new_step,
            "old_step": old_step,
            "lineage_id": lineage_id,
            "deploy_latency_s": deploy_latency_s,
            "replicas": details,
        }

    def status(self) -> dict:
        return {
            "replicas": [
                {
                    "name": rp.name,
                    "pid": rp.proc.pid if rp.proc is not None else None,
                    "port": rp.port,
                    "ready": rp.ready_evt.is_set(),
                    "launches": rp.launches,
                    "gave_up": rp.gave_up,
                    "retired": rp.retired,
                }
                for rp in self._snapshot()
            ],
        }


# ---------------------------------------------------------------------------
# fleet HTTP front end (what clients talk to)
# ---------------------------------------------------------------------------


class _FleetHandler(BaseHTTPRequestHandler):
    server_version = "ddlpc-fleet/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    @property
    def router(self) -> FleetRouter:
        return self.server.router  # type: ignore[attr-defined]

    @property
    def supervisor(self) -> Optional[ReplicaSupervisor]:
        return self.server.supervisor  # type: ignore[attr-defined]

    @property
    def aggregator(self) -> Optional[TelemetryAggregator]:
        return getattr(self.server, "aggregator", None)

    def _send(
        self, status: int, ctype: str, body: bytes, extra=()
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype or "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        for name, value in extra:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, obj: dict) -> None:
        self._send(status, "application/json", json.dumps(obj).encode())

    def do_GET(self) -> None:
        path = urlparse(self.path).path
        if path == "/healthz":
            h = self.router.healthz()
            self._send_json(200 if h["status"] == "ok" else 503, h)
        elif path == "/metrics":
            # One scrape answers for the whole fleet: the router's own
            # registry plus the aggregator's ddlpc_fleet_* rollups
            # (per-replica labels preserved) in one exposition.
            agg = self.aggregator
            accept = self.headers.get("Accept")
            if agg is not None and wants_prometheus(accept):
                body = (
                    self.router.registry.exposition() + agg.exposition()
                ).encode()
                self._send(200, PROMETHEUS_CTYPE, body)
                return
            ctype, body = render_metrics(
                self.router.registry,
                accept,
                json_fallback=lambda: self._json_metrics(agg),
            )
            self._send(200, ctype, body)
        elif path == "/fleet":
            out = self.router.healthz()
            if self.supervisor is not None:
                out["supervisor"] = self.supervisor.status()
            self._send_json(200, out)
        else:
            self._send_json(404, {"error": f"no route {path}"})

    def _json_metrics(self, agg: Optional[TelemetryAggregator]) -> dict:
        out = self.router.metrics.snapshot(advance=False)
        if agg is not None:
            out.update(agg.snapshot())
        return out

    def do_POST(self) -> None:
        parsed = urlparse(self.path)
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            if parsed.path == "/predict":
                # An external client's traceparent continues through the
                # fleet (its trace id spans client→router→replica);
                # otherwise a traced router mints a fresh one.
                info: dict = {}
                status, ctype, payload = self.router.dispatch(
                    body, parsed.query,
                    trace_context=parse_traceparent(
                        self.headers.get("traceparent")
                    ),
                    info=info,
                )
                # Every served prediction — cache hits included — names
                # the checkpoint step it came from.
                step = info.get("model_step")
                self._send(
                    status, ctype, payload,
                    extra=[(
                        obs_lineage.MODEL_STEP_HEADER,
                        str(step)
                        if step is not None
                        else obs_lineage.LINEAGE_UNKNOWN,
                    )],
                )
            elif parsed.path == "/reload":
                if self.supervisor is None:
                    self._send_json(
                        501, {"error": "no supervisor attached to this router"}
                    )
                    return
                try:
                    req = json.loads(body) if body else {}
                except ValueError as e:
                    self._send_json(
                        400, {"error": f"body is not valid JSON: {e}"}
                    )
                    return
                res = self.supervisor.rolling_reload(
                    step=req.get("step"), workdir=req.get("workdir")
                )
                self._send_json(200 if res.get("ok") else 409, res)
            else:
                self._send_json(404, {"error": f"no route {parsed.path}"})
        except BrokenPipeError:
            pass


def make_fleet_server(
    router: FleetRouter,
    supervisor: Optional[ReplicaSupervisor] = None,
    host: str = "127.0.0.1",
    port: int = 0,
    aggregator: Optional[TelemetryAggregator] = None,
) -> ServeHTTPServer:
    """Client-facing HTTP server over the router (+ optional supervisor
    for ``POST /reload`` rolling updates, + optional telemetry
    aggregator whose ddlpc_fleet_* rollups join ``GET /metrics``)."""
    server = ServeHTTPServer((host, port), _FleetHandler)
    server.router = router  # type: ignore[attr-defined]
    server.supervisor = supervisor  # type: ignore[attr-defined]
    server.aggregator = aggregator  # type: ignore[attr-defined]
    return server


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="python -m ddlpc_tpu.serve.fleet")
    p.add_argument("--config", help="FleetConfig JSON (configs/fleet_*.json)")
    p.add_argument("--workdir", help="training run to serve (overrides config)")
    p.add_argument("--replicas", type=int)
    p.add_argument("--host")
    p.add_argument("--port", type=int)
    args = p.parse_args(argv)

    cfg = FleetConfig()
    if args.config:
        with open(args.config) as f:
            cfg = FleetConfig.from_json(f.read())
    overrides = {
        k: v
        for k, v in (
            ("workdir", args.workdir),
            ("replicas", args.replicas),
            ("host", args.host),
            ("port", args.port),
        )
        if v is not None
    }
    if overrides:
        cfg = cfg.replace(**overrides)

    from ddlpc_tpu.train.observability import MetricsLogger

    fleet_dir = cfg.resolved_fleet_dir()
    os.makedirs(fleet_dir, exist_ok=True)
    logger = MetricsLogger(fleet_dir, basename="router")
    registry = MetricsRegistry()
    tracer = Tracer(
        enabled=cfg.trace,
        service="router",
        jsonl_path=os.path.join(fleet_dir, "router_spans.jsonl"),
        chrome_path=os.path.join(fleet_dir, "router_trace.json"),
    )
    router = FleetRouter(cfg, registry=registry, logger=logger, tracer=tracer)
    aggregator = None
    if cfg.aggregate_every_s > 0:
        aggregator = TelemetryAggregator(
            stale_after_s=cfg.aggregate_stale_after_s
        )
        # The router's own registry is a source too — its ddlpc_router_*
        # series roll up beside the replicas' ddlpc_serve_* families.
        aggregator.add_source("router", registry.exposition)
        aggregator.start(cfg.aggregate_every_s)
    sup = ReplicaSupervisor(
        cfg, router=router, logger=logger, aggregator=aggregator
    )
    n = sup.start(wait_ready=True)
    autoscaler = None
    if cfg.autoscale_enabled:
        from ddlpc_tpu.serve.autoscale import Autoscaler

        autoscaler = Autoscaler(
            cfg, router, sup, logger=logger, registry=registry
        )
        autoscaler.start()
    server = make_fleet_server(
        router, sup, cfg.host, cfg.port, aggregator=aggregator
    )
    print(
        f"fleet: {n}/{cfg.replicas} replicas ready; routing "
        f"http://{cfg.host}:{server.server_address[1]} -> {cfg.workdir}",
        flush=True,
    )

    def _shutdown(signum, frame):
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    try:
        server.serve_forever()
    finally:
        server.server_close()
        if autoscaler is not None:
            autoscaler.close()
        sup.stop()
        if aggregator is not None:
            aggregator.close()
        tracer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
