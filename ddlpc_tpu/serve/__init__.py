"""`ddlpc_tpu.serve` — batched, backpressured inference serving.

The training side of this framework replaces the reference's hand-rolled
socket cluster; this package is the inference counterpart of that ambition
(ROADMAP north star: "serves heavy traffic").  Layers, bottom-up:

- :mod:`engine`   — checkpoint restore, shape-bucketed jitted forward cache,
                    the overlap-blended sliding-window tiler (hoisted out of
                    ``predict.py``), and lock-guarded checkpoint hot-reload.
- :mod:`quantized` — int8/bf16 weight-quantized inference state: per-leaf
                    max-abs scales computed once per restore/reload,
                    dequant fused into the jitted forward (host-tier:
                    its jax imports are function-local, paid only when a
                    quantize path actually runs).
- :mod:`batching` — bounded admission queue + dynamic micro-batcher:
                    coalesce up to ``max_batch`` requests or ``max_wait_ms``,
                    whichever first; per-request deadlines; typed
                    ``Overloaded`` load-shedding; graceful drain.
- :mod:`cbatch`   — continuous batching: ``slots`` workers refill the
                    device pipeline the moment they free (no coalescing
                    timer), with interactive/batch priority classes and a
                    starvation bound.
- :mod:`metrics`  — latency quantiles (p50/p95/p99), queue depth, batch
                    occupancy, tiles/sec — emitted on the same JSONL stream
                    shape as ``train/observability.py``.
- :mod:`server`   — stdlib ``http.server`` front end (``/healthz``,
                    ``/predict``, ``/metrics``, ``/reload``) over a
                    ``ServingFrontend`` that ties the three together.
- :mod:`router`   — fleet routing tier: health/occupancy-aware dispatch
                    across N replicas with retry, hedging, and
                    per-replica circuit breakers (jax-free).
- :mod:`fleet`    — replica supervision (launch/classify/backoff/relaunch
                    via resilience/supervisor.py machinery) and the
                    zero-downtime rolling hot-reload protocol.
- :mod:`autoscale` — SLO-driven elastic fleet: a policy loop scaling the
                    replica count on burn rate / queue depth / slot-busy
                    signals through the supervisor's runtime
                    scale_up/scale_down (jax-free).
- :mod:`cache`    — content-addressed response cache in the router:
                    sha256(input bytes + serving step + quant mode) →
                    logits, LRU-bounded by bytes, flushed fleet-wide when
                    the serving step changes (jax-free).
"""

from ddlpc_tpu.serve.batching import (  # noqa: F401
    DeadlineExceeded,
    EngineClosed,
    MicroBatcher,
    Overloaded,
)
from ddlpc_tpu.serve.autoscale import Autoscaler  # noqa: F401
from ddlpc_tpu.serve.cache import ResponseCache, response_key  # noqa: F401
from ddlpc_tpu.serve.cbatch import ContinuousBatcher  # noqa: F401
from ddlpc_tpu.serve.engine import (  # noqa: F401
    InferenceEngine,
    sliding_window_logits,
)
from ddlpc_tpu.serve.metrics import ServeMetrics  # noqa: F401
from ddlpc_tpu.serve.router import (  # noqa: F401
    CircuitBreaker,
    FleetRouter,
    HTTPReplicaClient,
    ReplicaClient,
    ReplicaError,
)
