"""Weight-quantized inference path: int8 / bf16 params, fp32 math.

The training side already owns a quantization codec (ops/quantize.py) —
for *gradients*, with one whole-model scale because that is what the
reference put on the wire.  Serving weights want the transposed trade:
the tensors are static between reloads, so the scales can be computed
ONCE per restore (not per step), and per-leaf max-abs scales cost nothing
while being dramatically tighter than a global one (a conv kernel's
absmax and a BN bias's absmax differ by orders of magnitude — one shared
scale would flush the small leaves to a handful of lattice points).

Scheme, per float param leaf:

- ``int8``: ``scale = absmax(leaf) / 127`` (zero-guarded by
  ops/quantize.safe_divisor), ``q = clip(round(leaf / scale), ±127)``
  via ops/quantize.quantize_with_scale — the training codec's one
  lattice formula with per-leaf scales and levels=127 — stored as int8:
  4× smaller than fp32 in HBM, worst-case per-weight error
  ``absmax/254``;
- ``bf16``: round-to-nearest-even cast — 2× smaller, ~3 decimal digits;
- ``off``: identity (the engine never calls in here).

Dequantization is FUSED INTO THE JITTED FORWARD: the compiled program
takes the quantized tree, multiplies each leaf back to fp32 (one
elementwise op XLA fuses into the first consumer), and runs the model
unchanged — so the *resident* weights are int8/bf16 while the math keeps
the model's own compute dtype.  Only the quantized tree lives on device;
the fp32 restore target stays host-side between reloads.

Activation quantization (``quantize_activations``) casts the input
windows to bf16 inside the same jitted program — a knob, default off,
enabled only where the hard-task table says quality holds
(docs/SERVING.md "Continuous batching & quantized inference").

Batch-norm statistics are never quantized: they are a rounding error of
the params' footprint and their scale structure (running variances) is
exactly what coarse lattices destroy.

Tier note (analysis/tiers.py): this module is ``host``-tier — the engine
imports it eagerly, and the module's own jax imports are function-local
(paid only when a quantize/dequant path actually runs, the
obs/profiling idiom), so router/fleet stay provably jax-free.
"""

from __future__ import annotations

from typing import Any, NamedTuple

PyTree = Any

MODES = ("off", "int8", "bf16")


class QuantizedState(NamedTuple):
    """Device-resident quantized inference state (a pytree: NamedTuples
    of arrays jit cleanly).  ``scales`` carries one fp32 scalar per param
    leaf for int8 (all-ones placeholders for bf16, so the treedef is
    mode-independent)."""

    params: PyTree  # int8 or bf16 leaves, same structure as fp32 params
    scales: PyTree  # fp32 scalar per leaf
    batch_stats: PyTree  # fp32, untouched


def check_mode(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(
            f"unknown weight-quantization mode {mode!r} "
            f"(expected one of {MODES})"
        )
    return mode


def quantize_error_bound(mode: str) -> float:
    """Worst-case per-weight |dequant - original| as a fraction of the
    leaf's absmax: half an int8 lattice step, or bf16's 8-bit mantissa
    rounding.  The parity tests derive their tolerances from this."""
    check_mode(mode)
    if mode == "int8":
        return 0.5 / 127.0
    if mode == "bf16":
        return 2.0 ** -8  # relative rounding of a bf16 cast
    return 0.0


def quantize_state(state, mode: str) -> QuantizedState:
    """Quantize a restored TrainState's params for serving.

    Runs eagerly, ONCE per restore/reload — scales are data-dependent on
    the checkpoint, not on traffic.  Leaves arrive as whatever the
    checkpoint reader produced (host numpy); the returned tree is
    device-committed so forwards never re-upload.
    """
    import jax
    import jax.numpy as jnp

    from ddlpc_tpu.ops.quantize import quantize_with_scale, safe_divisor

    check_mode(mode)
    if mode == "off":
        raise ValueError("quantize_state needs mode 'int8' or 'bf16'")

    def q_leaf(p):
        p32 = jnp.asarray(p, jnp.float32)
        if mode == "bf16":
            return p32.astype(jnp.bfloat16), jnp.float32(1.0)
        # The training codec's lattice formula (snap, clip, zero-guard),
        # with levels=127 and one scale PER LEAF instead of one per
        # model — the serving transpose described in the module docstring.
        safe = safe_divisor(jnp.max(jnp.abs(p32)))
        q = quantize_with_scale(p32, safe, 127.0).astype(jnp.int8)
        return q, safe / 127.0

    pairs = jax.tree.map(q_leaf, state.params)
    params = jax.tree.map(lambda pr: pr[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
    scales = jax.tree.map(lambda pr: pr[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
    batch_stats = jax.tree.map(
        lambda b: jnp.asarray(b, jnp.float32), state.batch_stats
    )
    out = QuantizedState(params, scales, batch_stats)
    return jax.tree.map(jax.device_put, out)


def dequantize_params(params: PyTree, scales: PyTree, mode: str) -> PyTree:
    """fp32 params from the quantized tree — jittable; inside the
    compiled forward this is one fused multiply per leaf (the
    ops/quantize.decode runtime-scalar idiom, so dequantization is
    bit-identical across every bucket's program)."""
    import jax
    import jax.numpy as jnp

    if mode == "bf16":
        return jax.tree.map(lambda q: q.astype(jnp.float32), params)
    return jax.tree.map(
        lambda q, s: q.astype(jnp.float32) * s, params, scales
    )


def make_quantized_logits_fn(model, mode: str, quantize_activations: bool = False):
    """Jitted ``fn(qstate, images) -> logits`` with dequant fused in.

    The counterpart of train_step.make_logits_fn for a quantized engine:
    same output contract (raw logits [N, H, W, C]), different resident
    state.  One wrapper per (bucket, geometry) key, exactly like the
    fp32 path — the engine's jit cache does not care which it holds.
    """
    import jax
    import jax.numpy as jnp

    check_mode(mode)

    @jax.jit
    def logits_fn(qstate: QuantizedState, images: jax.Array) -> jax.Array:
        params = dequantize_params(qstate.params, qstate.scales, mode)
        if quantize_activations:
            images = images.astype(jnp.bfloat16)
        return model.apply(
            {"params": params, "batch_stats": qstate.batch_stats},
            images,
            train=False,
        )

    return logits_fn


def tree_nbytes(tree: PyTree) -> int:
    """Total resident bytes of a pytree of arrays (shape × itemsize —
    the obs/hbm.py accounting for the unsharded serving case)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def state_nbytes(state_or_q) -> dict:
    """``{params: bytes, batch_stats: bytes}`` for either a TrainState or
    a QuantizedState — what the engine publishes on
    ``ddlpc_hbm_bytes{kind}`` so a quantized rollout's HBM saving is a
    scrape, not a claim."""
    if isinstance(state_or_q, QuantizedState):
        return {
            "params": tree_nbytes(state_or_q.params)
            + tree_nbytes(state_or_q.scales),
            "batch_stats": tree_nbytes(state_or_q.batch_stats),
        }
    return {
        "params": tree_nbytes(state_or_q.params),
        "batch_stats": tree_nbytes(state_or_q.batch_stats),
    }
