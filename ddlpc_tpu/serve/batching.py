"""Dynamic micro-batcher: bounded queue, coalescing, deadlines, shedding.

The serving equivalent of the train loop's gradient-accumulation window:
individual requests (one fixed-size tile each) queue up, a single worker
thread coalesces up to ``max_batch`` of them or waits at most
``max_wait_ms`` from the oldest entry — whichever comes first — and runs ONE
forward for the whole batch.  Under light load a request pays at most
``max_wait_ms`` of coalescing latency; under heavy load batches fill
instantly and the wait never triggers.

Backpressure is explicit and typed, never implicit and unbounded:

- admission control: the queue is bounded at ``queue_limit``; a submit that
  would exceed it raises :class:`Overloaded` immediately (load-shedding —
  the client gets a fast typed "retry later", not a slow request);
- per-request deadlines: a request that is still queued past its deadline
  completes with :class:`DeadlineExceeded` instead of occupying a batch
  slot it can no longer use;
- graceful drain: ``close(drain=True)`` stops admission, lets the worker
  finish everything already queued, then joins — in-flight work is never
  dropped on shutdown.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from contextlib import nullcontext
from typing import Callable, List, Optional, Sequence

from ddlpc_tpu.analysis import lockcheck

_NULL_CTX = nullcontext()


class Overloaded(RuntimeError):
    """Admission queue full — request shed; retry with backoff."""


class DeadlineExceeded(RuntimeError):
    """Request expired in queue before a batch slot reached it."""


class EngineClosed(RuntimeError):
    """Batcher is shutting down; no new work accepted."""


class _Item:
    __slots__ = ("payload", "future", "enqueued", "deadline", "t_trace",
                 "trace_id")

    def __init__(
        self, payload, deadline: Optional[float], now: float,
        t_trace: float = 0.0, trace_id: Optional[str] = None,
    ):
        self.payload = payload
        self.future: Future = Future()
        self.enqueued = now
        self.deadline = deadline
        # Enqueue time on the tracer's clock (tracing enabled only): the
        # worker records the cross-thread enqueue→batch-take wait with it.
        self.t_trace = t_trace
        # Request trace id captured on the submitting thread (the one
        # holding the tracer binding) — worker-thread batch spans name
        # the request traces they serve via this (obs/merge.py).
        self.trace_id = trace_id


def _fail(future: Future, exc: Exception) -> None:
    """set_exception tolerating a concurrent client cancel().

    A PENDING future can be cancelled by its client between any
    ``cancelled()`` check and the ``set_exception`` call (check-then-act
    race); the resulting InvalidStateError must never kill the worker
    thread — a cancelled future needs no completion anyway."""
    try:
        future.set_exception(exc)
    except Exception:
        pass


@lockcheck.guarded
class MicroBatcher:
    """Coalesce submitted payloads into batched ``forward`` calls.

    ``forward(list_of_payloads) -> sequence_of_results`` runs on the worker
    thread; result ``i`` resolves the future of payload ``i``.  A forward
    exception fails every request in that batch (the typed errors above
    never reach ``forward``).

    Shared state is guarded by ``_cond`` (``# guarded-by:`` annotations
    below are enforced under ``DDLPC_LOCKCHECK=1`` — docs/ANALYSIS.md).
    """

    def __init__(
        self,
        forward: Callable[[List], Sequence],
        max_batch: int = 8,
        max_wait_ms: float = 5.0,
        queue_limit: int = 64,
        metrics=None,
        tracer=None,
        start: bool = True,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        self._forward = forward
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.queue_limit = int(queue_limit)
        self.metrics = metrics
        # Optional span tracer (obs/tracing.py): the worker records one
        # cross-thread ``batch_coalesce`` span per batch (oldest member's
        # enqueue → batch take) and a ``jit_execute`` span around forward.
        self.tracer = tracer
        self._q: deque = deque()  # guarded-by: _cond
        self._cond = lockcheck.condition("MicroBatcher._cond")
        self._closing = False  # guarded-by: _cond
        # batched forward calls issued (read by tests/metrics/chaos hooks
        # from other threads, so the increment holds the lock too)
        self.forward_count = 0  # guarded-by: _cond
        self._thread = threading.Thread(
            target=self._run, name="serve-batcher", daemon=True
        )
        self._started = False
        if start:
            self.start()

    # ---- admission ---------------------------------------------------------

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()

    def submit(self, payload, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one payload; returns its Future.

        Raises :class:`Overloaded` (queue full) or :class:`EngineClosed`
        (draining/closed) instead of blocking — admission never waits.
        """
        return self.submit_many([payload], deadline_ms=deadline_ms)[0]

    def submit_many(
        self, payloads: Sequence, deadline_ms: Optional[float] = None
    ) -> List[Future]:
        """All-or-nothing admission for a multi-tile request.

        A scene that tiles into k windows either gets all k queue slots or
        is shed whole — partial admission would burn forward capacity on
        windows whose request can no longer complete.
        """
        if not payloads:
            return []
        now = time.monotonic()
        deadline = None if not deadline_ms else now + deadline_ms / 1000.0
        with self._cond:
            if self._closing:
                raise EngineClosed("batcher is draining; not accepting work")
            if len(self._q) + len(payloads) > self.queue_limit:
                if self.metrics is not None:
                    self.metrics.record_shed(len(payloads))
                raise Overloaded(
                    f"queue full ({len(self._q)}/{self.queue_limit} + "
                    f"{len(payloads)} new); retry with backoff"
                )
            t_trace = 0.0
            trace_id = None
            if self.tracer is not None and self.tracer.enabled:
                t_trace = self.tracer.now()
                trace_id = self.tracer.current_trace_id()
            items = [
                _Item(p, deadline, now, t_trace, trace_id) for p in payloads
            ]
            self._q.extend(items)
            if self.metrics is not None:
                self.metrics.set_queue_depth(len(self._q))
            self._cond.notify_all()
        return [it.future for it in items]

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    # ---- worker ------------------------------------------------------------

    def _take_batch(self) -> Optional[List[_Item]]:
        """Block until a batch is ready (full, aged past max_wait, or
        draining) or the batcher is closed and empty (returns None)."""
        with self._cond:
            while not self._q and not self._closing:
                self._cond.wait(0.05)
            if not self._q:
                return None  # closing and drained
            # Coalesce: wait for a full batch, but never hold the OLDEST
            # request past max_wait.  Draining flushes immediately.
            target = self._q[0].enqueued + self.max_wait_s
            while len(self._q) < self.max_batch and not self._closing:
                remaining = target - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch = [
                self._q.popleft()
                for _ in range(min(self.max_batch, len(self._q)))
            ]
            if self.metrics is not None:
                self.metrics.set_queue_depth(len(self._q))
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            now = time.monotonic()
            live: List[_Item] = []
            for it in batch:
                if it.deadline is not None and now > it.deadline:
                    if self.metrics is not None:
                        self.metrics.record_deadline()
                    _fail(
                        it.future,
                        DeadlineExceeded(
                            f"queued {now - it.enqueued:.3f}s, past deadline"
                        ),
                    )
                elif not it.future.set_running_or_notify_cancel():
                    # Client cancelled while queued (e.g. a sibling window
                    # of its scene already failed) — don't burn a slot.
                    continue
                else:
                    live.append(it)
            if not live:
                continue
            with self._cond:
                self.forward_count += 1
            tracer = self.tracer
            tids = sorted({it.trace_id for it in live if it.trace_id})
            if tracer is not None and tracer.enabled:
                # Cross-thread coalesce wait: the oldest live member's
                # enqueue (client thread) → this batch take (worker).
                tracer.add_span(
                    "batch_coalesce",
                    live[0].t_trace,
                    tracer.now(),
                    batch=len(live),
                    **({"trace_ids": tids} if tids else {}),
                )
            span = (
                tracer.span(
                    "jit_execute", batch=len(live),
                    **({"trace_ids": tids} if tids else {}),
                )
                if tracer is not None
                else _NULL_CTX
            )
            try:
                with span:
                    results = list(self._forward([it.payload for it in live]))
                if len(results) != len(live):
                    # A short/long result list would otherwise leave some
                    # futures unresolved FOREVER — turn the contract breach
                    # into a typed batch failure instead of a silent hang.
                    raise RuntimeError(
                        f"forward returned {len(results)} results for "
                        f"{len(live)} payloads"
                    )
            except Exception as e:  # fail the batch, keep serving
                for it in live:
                    _fail(it.future, e)
                continue
            for it, res in zip(live, results):
                it.future.set_result(res)
            # Latency is recorded per REQUEST by the frontend (a scene is
            # one request, many tiles); the batcher owns batch-shape stats.
            if self.metrics is not None:
                self.metrics.record_batch(len(live), self.max_batch)

    # ---- shutdown ----------------------------------------------------------

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop admission; drain (default) or abandon the queue; join.

        With ``drain=False`` queued requests fail with :class:`EngineClosed`
        — still a typed completion, never a hang.
        """
        if drain and not self._started:
            # A deferred-start batcher (tests) still owes its queue a drain.
            self.start()
        with self._cond:
            self._closing = True
            if not drain:
                while self._q:
                    it = self._q.popleft()
                    _fail(
                        it.future, EngineClosed("batcher closed without drain")
                    )
            self._cond.notify_all()
        if self._started:
            self._thread.join(timeout)

    def __enter__(self) -> "MicroBatcher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)
