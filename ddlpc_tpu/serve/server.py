"""Serving front end: stdlib HTTP over the engine + batcher + metrics.

Two layers so the protocol stays swappable:

- :class:`ServingFrontend` — protocol-agnostic: full-scene predict (plan →
  batched windows → stitch), health/metrics readouts, hot-reload, graceful
  drain.  Tests and the load generator drive this directly.
- ``http.server`` handler — ``GET /healthz``, ``GET /metrics``,
  ``POST /predict`` (npy image body → npy class-map body),
  ``POST /reload``.  A deliberately boring stdlib front end: the workload
  is compute-bound on the accelerator, so a threading HTTP server whose
  request threads block on batcher futures is enough — the batcher is the
  throughput engine, not the socket layer.

Overload semantics on the wire: ``Overloaded`` → 503 + Retry-After,
``DeadlineExceeded`` → 504, draining → 503.  Clients get a fast typed
rejection, never an unbounded queue wait (ISSUE 1 tentpole contract).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import io
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ddlpc_tpu.config import ServeConfig
from ddlpc_tpu.obs import lineage as obs_lineage
from ddlpc_tpu.obs import profiling as _profiling
from ddlpc_tpu.obs.health import Alert as HealthAlert
from ddlpc_tpu.obs.health import HealthMonitor
from ddlpc_tpu.obs.http import render_metrics
from ddlpc_tpu.obs.registry import MetricsRegistry
from ddlpc_tpu.obs.tracing import (
    TRACEPARENT_HEADER,
    Tracer,
    parse_traceparent,
)
from ddlpc_tpu.serve.batching import (
    DeadlineExceeded,
    EngineClosed,
    MicroBatcher,
    Overloaded,
)
from ddlpc_tpu.serve.cbatch import ContinuousBatcher, check_priority
from ddlpc_tpu.serve.engine import (
    InferenceEngine,
    Stitcher,
    window_plan,
)
from ddlpc_tpu.serve.metrics import ServeMetrics


class ServingFrontend:
    """Engine + batcher + metrics behind one protocol-agnostic API."""

    def __init__(
        self,
        engine: InferenceEngine,
        cfg: Optional[ServeConfig] = None,
        logger=None,
    ):
        self.engine = engine
        self.cfg = cfg or ServeConfig()
        # Unified telemetry (ddlpc_tpu/obs): a Prometheus-style registry
        # every metrics hook publishes into (GET /metrics negotiates text
        # exposition vs the legacy JSON snapshot), a span tracer for the
        # request path, and health detectors for queue saturation.
        self.registry = MetricsRegistry()
        # Traces land next to the metrics stream: metrics_dir when set (the
        # fleet gives each replica its own — N replicas must never
        # interleave one serve_spans.jsonl), else the workdir as before.
        trace_dir = self.cfg.metrics_dir or self.cfg.workdir
        self.tracer = Tracer(
            enabled=self.cfg.trace,
            service="serve",
            jsonl_path=os.path.join(trace_dir, "serve_spans.jsonl"),
            chrome_path=os.path.join(trace_dir, "serve_trace.json"),
        )
        self.metrics = ServeMetrics(
            window=self.cfg.metrics_window, registry=self.registry
        )
        # Shape-bucketed jit cache visibility (getattr: tests drive the
        # frontend with minimal fake engines).
        attach = getattr(engine, "attach_registry", None)
        if attach is not None:
            attach(self.registry)
        # Admission loop: 'continuous' (serve/cbatch.py — slot-based
        # refill, priority classes) or PR 1's coalesce-and-wait
        # MicroBatcher.  Both expose the same submit/drain/typed-error
        # surface; everything below is batcher-agnostic.
        if self.cfg.batcher == "continuous":
            self.batcher = ContinuousBatcher(
                engine.forward_windows,
                max_batch=self.cfg.max_batch,
                queue_limit=self.cfg.queue_limit,
                batch_queue_limit=self.cfg.batch_queue_limit,
                slots=self.cfg.slots,
                starvation_every=self.cfg.starvation_every,
                metrics=self.metrics,
                tracer=self.tracer,
            )
        elif self.cfg.batcher == "coalesce":
            self.batcher = MicroBatcher(
                engine.forward_windows,
                max_batch=self.cfg.max_batch,
                max_wait_ms=self.cfg.max_wait_ms,
                queue_limit=self.cfg.queue_limit,
                metrics=self.metrics,
                tracer=self.tracer,
            )
        else:
            raise ValueError(
                f"unknown batcher {self.cfg.batcher!r} "
                f"(expected 'continuous' or 'coalesce')"
            )
        self.logger = logger
        if logger is not None and getattr(logger, "registry", None) is None:
            # The serve CLI builds its logger before this frontend (and its
            # registry) exists — wire it here so the periodic snapshot
            # records (p50/p95/p99 quantiles) reach the Prometheus
            # exposition as ddlpc_serve_* gauges too.
            logger.attach_registry(self.registry)
        self.health = HealthMonitor(
            logger=logger, registry=self.registry, service="serve"
        )
        self.draining = False
        # Failed hot-reloads (corrupt/truncated/missing checkpoints): the
        # engine keeps serving the CURRENT params; the failure is counted,
        # alerted, and surfaced on /healthz — never raised into a handler.
        self._reload_errors = self.registry.counter(
            "ddlpc_serve_reload_errors_total",
            "Hot-reload attempts that failed (engine kept serving the "
            "previous weights), by error type.",
            labelnames=("error",),
        )
        self.last_reload_error: Optional[str] = None
        self._profile_lock = threading.Lock()
        self._profile_n = 0
        # Quantized deploys leave an audit record of what is resident:
        # mode + actual byte footprint, once at start and per reload.
        self._log_quant()
        self._emit_stop = threading.Event()
        self._emitter: Optional[threading.Thread] = None
        if logger is not None and self.cfg.metrics_every_s > 0:
            self._emitter = threading.Thread(
                target=self._emit_loop, name="serve-metrics", daemon=True
            )
            self._emitter.start()

    def _log_quant(self) -> None:
        """kind="serve_quant" audit record: which weight-quant mode is
        live and what the resident inference state actually weighs."""
        mode = getattr(self.engine, "quantize_mode", "off")
        if self.logger is None or mode == "off":
            return
        rec = {
            "kind": "serve_quant",
            "mode": mode,
            "quantize_activations": bool(
                getattr(self.engine, "quantize_activations", False)
            ),
            "checkpoint_step": self.engine.checkpoint_step,
        }
        hbm = getattr(self.engine, "hbm_bytes", None)
        if hbm is not None:
            rec.update({f"{k}_bytes": int(v) for k, v in hbm().items()})
        self.logger.log(rec, echo=False)

    def _emit_loop(self) -> None:
        while not self._emit_stop.wait(self.cfg.metrics_every_s):
            self.metrics.emit(self.logger)
            # Queue-saturation detection rides the emit cadence: a single
            # full sample is a burst, N consecutive saturated samples at
            # this cadence mean shedding is imminent (obs/health.py).
            self.health.observe_queue(
                self.batcher.queue_depth, self.cfg.queue_limit
            )
            self._publish_slot_busy()

    def _publish_slot_busy(self) -> None:
        """Per-slot busy fractions over the emit window →
        ``ddlpc_serve_slot_busy_fraction{slot}`` (continuous batcher only;
        getattr-guarded like every other optional batcher surface)."""
        fractions_fn = getattr(self.batcher, "slot_busy_fractions", None)
        if fractions_fn is not None:
            self.metrics.set_slot_busy(fractions_fn())

    # ---- request paths -----------------------------------------------------

    def predict_logits(
        self,
        image: np.ndarray,
        overlap: Optional[float] = None,
        priority: str = "interactive",
    ) -> np.ndarray:
        """Full-scene logits with every window routed through the batcher —
        windows from concurrent scenes coalesce into shared forwards.
        ``priority='batch'`` files the scene's windows into the bulk
        admission queue (continuous batcher; the coalesce batcher has one
        queue and the class is accounting-only)."""
        image = np.asarray(image, np.float32)
        check_priority(priority)
        if image.ndim != 3:
            raise ValueError(f"expected [H, W, C] image, got {image.shape}")
        if image.shape[-1] != self.engine.channels:
            raise ValueError(
                f"expected {self.engine.channels} channels, got "
                f"{image.shape[-1]}"
            )
        overlap = self.cfg.overlap if overlap is None else overlap
        th, tw = self.engine.tile
        t0 = time.monotonic()
        # Root span per scene request; window_plan/enqueue/stitch nest
        # under it on this thread (the batcher's coalesce/execute spans are
        # cross-thread and stand alone on the worker's track).
        with self.tracer.span("serve_request") as req_span:
            out, n_tiles = self._predict_logits_inner(
                image, overlap, th, tw, req_span, priority
            )
        self.metrics.record_request(
            time.monotonic() - t0, tiles=n_tiles, priority=priority
        )
        return out

    def _predict_logits_inner(self, image, overlap, th, tw, req_span,
                              priority="interactive"):
        with self.tracer.span("window_plan"):
            padded, origins, (h, w) = window_plan(
                image, self.engine.tile, overlap
            )
        # Chunked admission: each chunk is admitted all-or-nothing (a shed
        # chunk never half-occupies the queue), but a scene that tiles into
        # more windows than the queue holds is NOT permanently rejected —
        # it streams through in chunks of at most half the queue, which
        # also stops one huge scene from monopolizing admission.  Blending
        # happens as futures resolve, so peak memory is the accumulator +
        # one in-flight chunk.  result() gets a margin on top of the queue
        # deadline so a wedged worker surfaces as an error, not a hang.
        st = Stitcher(self.engine.tile, padded.shape[:2], (h, w))
        chunk_size = max(1, self.cfg.queue_limit // 2)
        timeout = (
            self.cfg.deadline_ms / 1000.0 + 60.0
            if self.cfg.deadline_ms
            else None
        )
        submit_kwargs = (
            {"priority": priority}
            if isinstance(self.batcher, ContinuousBatcher)
            else {}
        )
        for i in range(0, len(origins), chunk_size):
            chunk = origins[i : i + chunk_size]
            windows = [padded[y : y + th, x : x + tw] for y, x in chunk]
            with self.tracer.span("enqueue", windows=len(windows)):
                futures = self.batcher.submit_many(
                    windows, deadline_ms=self.cfg.deadline_ms or None,
                    **submit_kwargs,
                )
            try:
                with self.tracer.span("stitch", windows=len(windows)):
                    for origin, fut in zip(chunk, futures):
                        st.add(origin, fut.result(timeout=timeout))
            except BaseException:
                # The scene already failed: cancel still-queued sibling
                # windows so the batcher stops burning capacity on a
                # request that got its error response.
                for fut in futures:
                    fut.cancel()
                raise
        out = st.finish()
        req_span.set(tiles=len(origins))
        return out, len(origins)

    def predict_classes(
        self,
        image: np.ndarray,
        overlap: Optional[float] = None,
        priority: str = "interactive",
    ) -> np.ndarray:
        return np.argmax(
            self.predict_logits(image, overlap, priority=priority), axis=-1
        ).astype(np.int32)

    def reload(self, workdir: Optional[str] = None, step=None) -> dict:
        """Hot-reload; NEVER raises (ISSUE 7 satellite).

        ``step`` pins an explicit checkpoint step (the fleet's rolling-
        reload rollback uses it to push every replica back to the old
        weights); default is the newest.

        The checkpoint reader already quarantines a corrupt newest blob and
        falls back to the next-newest (train/checkpoint.py); this catch is
        the last line — no checkpoints left, unreadable disk, anything —
        and its contract is: keep serving the current weights, return a
        structured ``{"error": ...}`` the HTTP layer maps to a non-200,
        count it, and alert.  The engine's state is untouched on failure
        (the restore runs off-lock BEFORE the reference swap).
        """
        try:
            meta = self.engine.reload(workdir, step=step)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            self.last_reload_error = err
            self._reload_errors.inc(error=type(e).__name__)
            self.health.emit(
                HealthAlert(
                    alert="reload_failed",
                    severity="warn",
                    message=f"hot-reload failed, serving previous weights: {err}",
                    value=float(self.engine.version),
                    threshold=0.0,
                )
            )
            return {
                "error": err,
                "error_type": type(e).__name__,
                # What we are STILL serving — the caller's recovery signal.
                "version": self.engine.version,
                "checkpoint_step": self.engine.checkpoint_step,
            }
        self.last_reload_error = None
        if meta.get("quarantined_steps"):
            # The reader fell back past corrupt blob(s): serving continues
            # on an older checkpoint — loud, but not an error.
            self.health.emit(
                HealthAlert(
                    alert="checkpoint_quarantined",
                    severity="warn",
                    message=(
                        f"reload quarantined corrupt checkpoint step(s) "
                        f"{meta['quarantined_steps']}, restored step "
                        f"{meta.get('step')}"
                    ),
                    value=float(meta.get("step") or 0),
                    threshold=0.0,
                )
            )
        if self.logger is not None:
            self.logger.log(
                {
                    "kind": "serve_reload",
                    "version": self.engine.version,
                    "step": meta.get("step"),
                    "restore_seconds": meta.get("restore_seconds"),
                    "restore_format": meta.get("restore_format"),
                    # Flat lineage join key (the record itself stays flat
                    # per obs/schema.py) — how obs/merge.py ties this
                    # reload to the checkpoint save span that produced it.
                    **obs_lineage.flatten(meta.get("lineage")),
                },
                echo=False,
            )
        self._log_quant()  # fresh scales/footprint after the swap
        return meta

    def healthz(self) -> dict:
        # Queue depth, limit, and windowed batch occupancy ride along so
        # the fleet router's occupancy-aware dispatch has ONE cheap scrape
        # endpoint instead of parsing the full /metrics exposition; the
        # per-priority depths and quant mode keep that one-scrape contract
        # sufficient for priority-aware dispatch and quantized rollouts.
        slot_busy = self.metrics.slot_busy  # replaced atomically on emit
        depths_fn = getattr(self.batcher, "queue_depths", None)
        depths = (
            depths_fn()
            if depths_fn is not None
            else {"interactive": self.batcher.queue_depth, "batch": 0}
        )
        return {
            "status": "draining" if self.draining else "ok",
            "version": self.engine.version,
            # queue_depth derives from the SAME read as the per-class
            # depths — one scrape must never contradict itself (the
            # router ranks on the total and sheds on the classes).
            "checkpoint_step": self.engine.checkpoint_step,
            "tile": list(self.engine.tile),
            "channels": self.engine.channels,
            "queue_depth": sum(depths.values()),
            "queue_depth_interactive": depths.get("interactive", 0),
            "queue_depth_batch": depths.get("batch", 0),
            "queue_limit": self.cfg.queue_limit,
            "quant_mode": getattr(self.engine, "quantize_mode", "off"),
            "batch_occupancy": self.metrics.occupancy(),
            # Mean of the LAST PUBLISHED per-slot busy fractions (emit
            # cadence) — reading the batcher here would consume its
            # readout window out from under the metrics emitter.  None
            # until the first emit, or without a continuous batcher; the
            # autoscaler treats None as "no signal".
            "slot_busy_fraction": (
                sum(slot_busy.values()) / len(slot_busy)
                if slot_busy
                else None
            ),
            "compiled_shapes": self.engine.compiled_shapes,
            "last_reload_error": self.last_reload_error,
            "alerts": list(self.health.alerts),
            # Lineage of the serving weights, FLAT (the router scrapes
            # these fields into its freshness gauges; pre-lineage
            # checkpoints surface the explicit unknown marker).
            **obs_lineage.flatten(getattr(self.engine, "lineage", None)),
        }

    def debug_trace(self, steps: Optional[int] = None, timeout_s: float = 30.0) -> dict:
        """On-demand profiler capture over the next ``steps`` batched
        forwards: jax.profiler trace → xplane self-time aggregation → the
        committed top-ops format, written as ``serve_top_ops_<n>.json`` in
        the workdir.  Returns the report (an ``error`` field instead of an
        exception for every failure mode — a second concurrent capture, a
        backend that cannot trace, no traffic within the timeout)."""
        steps = int(steps) if steps else self.cfg.profile_steps
        with self._profile_lock:
            self._profile_n += 1
            n = self._profile_n
        trace_dir = os.path.join(self.cfg.workdir, f"serve_profile_{n:03d}")
        target = self.batcher.forward_count + steps
        try:
            res = _profiling.capture(
                trace_dir,
                until=lambda: self.batcher.forward_count >= target,
                timeout_s=timeout_s,
            )
        except _profiling.CaptureBusy as e:
            return {"error": str(e)}
        if "error" in res:
            return res
        captured = steps if not res.get("timed_out") else max(
            self.batcher.forward_count - (target - steps), 1
        )
        report = _profiling.aggregate(
            trace_dir, steps=captured, tag=f"serve_ondemand_{n:03d}"
        )
        report["timed_out"] = res.get("timed_out", False)
        report["wall_s"] = res.get("seconds")
        path = os.path.join(self.cfg.workdir, f"serve_top_ops_{n:03d}.json")
        try:
            from ddlpc_tpu.utils.fsio import atomic_write_json

            atomic_write_json(path, report)
            report["report_path"] = path
        except OSError as e:
            report.setdefault("error", f"report not written: {e}")
        if self.logger is not None:
            self.logger.log(
                {
                    "kind": "profile",
                    "report_path": report.get("report_path"),
                    "steps_traced": captured,
                    "per_step_ms": report.get("per_step_ms"),
                    "error": report.get("error"),
                },
                echo=False,
            )
        return report

    def close(self, drain: bool = True) -> None:
        """Stop admission, finish queued work (drain=True), stop emitting."""
        self.draining = True
        self.batcher.close(drain=drain)
        self._emit_stop.set()
        if self._emitter is not None:
            self._emitter.join(timeout=5.0)
        if self.logger is not None:
            self.metrics.emit(self.logger)
        # Traced deploys drop serve_trace.json on shutdown (flush-and-close
        # is a no-op for a disabled tracer).
        self.tracer.close()


# ---- HTTP layer -------------------------------------------------------------


def _load_npy(body: bytes) -> np.ndarray:
    return np.load(io.BytesIO(body), allow_pickle=False)


def _dump_npy(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


class ServeHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that counts in-flight requests.

    Idle keep-alive connections hold no count — only a request actually
    being handled does — so the graceful SIGTERM drain can wait for real
    work without being wedged by a client that simply left its connection
    open.  Handler threads stay daemonic; the drain waits on THIS counter,
    not thread joins."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._inflight = 0
        self._inflight_cond = threading.Condition()

    def request_began(self) -> None:
        with self._inflight_cond:
            self._inflight += 1

    def request_finished(self) -> None:
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    @property
    def inflight(self) -> int:
        with self._inflight_cond:
            return self._inflight

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until no request is being handled (True) or ``timeout``
        expires with work still in flight (False)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
            return True


class _Handler(BaseHTTPRequestHandler):
    server_version = "ddlpc-serve/1"
    protocol_version = "HTTP/1.1"

    @property
    def frontend(self) -> ServingFrontend:
        return self.server.frontend  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # quiet by default; metrics cover it
        pass

    def do_GET(self) -> None:
        # In-flight accounting wraps the dispatch (handler → response
        # write), NOT the connection: an idle keep-alive socket blocked in
        # readline() between requests holds no count, so the graceful
        # drain waits for real work only.
        began = getattr(self.server, "request_began", None)
        if began is None:
            self._dispatch_get()
            return
        began()
        try:
            self._dispatch_get()
        finally:
            self.server.request_finished()

    def do_POST(self) -> None:
        began = getattr(self.server, "request_began", None)
        if began is None:
            self._dispatch_post()
            return
        began()
        try:
            self._dispatch_post()
        finally:
            self.server.request_finished()

    def _send_json(self, code: int, obj: dict, extra=()) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_npy(self, arr: np.ndarray, extra=()) -> None:
        body = _dump_npy(arr)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-npy")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _dispatch_get(self) -> None:
        parsed = urlparse(self.path)
        path = parsed.path
        if path == "/healthz":
            h = self.frontend.healthz()
            self._send_json(200 if h["status"] == "ok" else 503, h)
        elif path == "/metrics":
            # Content-negotiated (obs/http.py): JSON snapshot stays the
            # default (existing tooling and the bench parse it); an Accept
            # header naming text/plain or openmetrics — what Prometheus'
            # scraper sends — selects the text exposition.  advance=False:
            # a scrape must not reset the rate interval the periodic JSONL
            # emitter (and the bench) measure over.
            ctype, body = render_metrics(
                self.frontend.registry,
                self.headers.get("Accept"),
                json_fallback=lambda: self.frontend.metrics.snapshot(
                    advance=False
                ),
            )
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/debug/trace":
            q = parse_qs(parsed.query)
            try:
                steps = int(q["steps"][0]) if "steps" in q else 0
                timeout_s = (
                    float(q["timeout_s"][0]) if "timeout_s" in q else 30.0
                )
            except ValueError:
                self._send_json(
                    400, {"error": "steps/timeout_s must be numeric"}
                )
                return
            # Runs the capture on THIS handler thread (the server is
            # threading; other requests keep flowing — they are the very
            # traffic being profiled).
            self._send_json(200, self.frontend.debug_trace(steps, timeout_s))
        else:
            self._send_json(404, {"error": f"no route {path}"})

    def _dispatch_post(self) -> None:
        parsed = urlparse(self.path)
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            if parsed.path == "/predict":
                self._predict(parsed, body)
            elif parsed.path == "/reload":
                self._reload(body)
            else:
                self._send_json(404, {"error": f"no route {parsed.path}"})
        except BrokenPipeError:
            pass

    def _predict(self, parsed, body: bytes) -> None:
        try:
            image = _load_npy(body)
        except Exception as e:
            self._send_json(400, {"error": f"body is not a valid .npy: {e}"})
            return
        q = parse_qs(parsed.query)
        # Cross-process trace context (ISSUE 14): a traceparent header from
        # the fleet router binds this handler thread to the REQUEST's
        # trace id, so serve_request and its children join the router's
        # timeline.  Malformed/absent headers degrade to a local trace.
        ctx = parse_traceparent(self.headers.get(TRACEPARENT_HEADER))
        trace_id, parent_hex = ctx if ctx is not None else (None, None)
        try:
            overlap = float(q["overlap"][0]) if "overlap" in q else None
            priority = q["priority"][0] if "priority" in q else "interactive"
            with self.frontend.tracer.bind(trace_id, parent_hex):
                pred = self.frontend.predict_classes(
                    image, overlap=overlap, priority=priority
                )
        except Overloaded as e:
            self._send_json(503, {"error": str(e)}, extra=[("Retry-After", "1")])
        except (DeadlineExceeded, TimeoutError,
                concurrent.futures.TimeoutError) as e:
            # futures.TimeoutError is NOT the builtin before 3.11; both mean
            # the same here — the worker didn't produce a result in time.
            self._send_json(504, {"error": str(e) or "timed out"})
        except EngineClosed as e:
            self._send_json(503, {"error": str(e)})
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
        except Exception as e:  # engine/XLA failure: a 500, not a dropped
            # connection (socketserver would close the socket replyless and
            # lose any pipelined keep-alive request with it)
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
        else:
            # Provenance header (ISSUE 17): every prediction names the
            # training step that produced it; pre-lineage checkpoints get
            # the explicit unknown marker, never a missing header.
            step = getattr(self.frontend.engine, "checkpoint_step", None)
            self._send_npy(
                pred,
                extra=[(
                    obs_lineage.MODEL_STEP_HEADER,
                    str(step) if step is not None
                    else obs_lineage.LINEAGE_UNKNOWN,
                )],
            )

    def _reload(self, body: bytes) -> None:
        try:
            req = json.loads(body) if body else {}
        except ValueError as e:
            self._send_json(400, {"error": f"body is not valid JSON: {e}"})
            return
        # frontend.reload catches restore failures into a structured
        # {"error": ...} while the engine keeps serving the old weights —
        # mapped to a non-200 here so callers see the failure, but the
        # serving process never dies over a bad blob.  The outer guard is
        # the last resort for its SUCCESS path (metrics log, alert emit —
        # e.g. ENOSPC mid-write): a JSON 500 beats a dropped socket.
        try:
            meta = self.frontend.reload(req.get("workdir"), step=req.get("step"))
        except Exception as e:
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if "error" in meta:
            code = 404 if meta.get("error_type") == "FileNotFoundError" else 503
            self._send_json(code, meta)
            return
        resp = {
            "version": self.frontend.engine.version,
            "step": meta.get("step"),
            # What the swap cost and which on-disk format served it
            # (train/checkpoint.py dispatching reader).
            "restore_seconds": meta.get("restore_seconds"),
            "restore_format": meta.get("restore_format"),
        }
        if isinstance(meta.get("lineage"), dict):
            # Nested is fine in HTTP JSON (the flat contract binds JSONL
            # streams only): the fleet's rolling reload reads saved_at
            # from here to measure checkpoint-durable → fleet-serving.
            resp["lineage"] = meta["lineage"]
        if meta.get("quantize"):
            # A quantized engine's reload answer says what is now
            # resident (scales were recomputed from the new checkpoint).
            resp["quantize"] = meta["quantize"]
        if meta.get("quarantined_steps"):
            # Succeeded via fallback: corrupt newer blob(s) were renamed
            # *.bad and an older checkpoint restored.
            resp["quarantined_steps"] = meta["quarantined_steps"]
        self._send_json(200, resp)


def make_server(
    frontend: ServingFrontend, host: str = "127.0.0.1", port: int = 0
) -> ServeHTTPServer:
    """Bind a threading HTTP server over ``frontend`` (port 0 = ephemeral)."""
    server = ServeHTTPServer((host, port), _Handler)
    server.frontend = frontend  # type: ignore[attr-defined]
    return server


def drain_and_close(
    server: ServeHTTPServer,
    frontend: ServingFrontend,
    timeout_s: float = 30.0,
) -> bool:
    """Graceful shutdown after the accept loop has stopped (ISSUE 10
    satellite): mark draining (``/healthz`` flips to 503 for anything that
    still scrapes), let in-flight HTTP requests finish writing their
    responses, drain the batcher's queued work, flush the final metrics
    snapshot, release the socket.  Returns False if ``timeout_s`` expired
    with requests still in flight (the process exits anyway — a wedged
    client must not hold shutdown hostage)."""
    frontend.draining = True
    clean = server.wait_idle(timeout=timeout_s)
    # Everything admitted before the accept loop stopped is now either
    # answered or queued in the batcher; close(drain=True) finishes the
    # queue and flushes the final snapshot to serve_metrics.jsonl.
    frontend.close(drain=True)
    server.server_close()
    return clean


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m ddlpc_tpu.serve.server")
    p.add_argument("--config", help="ServeConfig JSON (configs/serve_*.json)")
    p.add_argument("--workdir", help="training run to serve (overrides config)")
    p.add_argument("--host")
    p.add_argument("--port", type=int)
    p.add_argument(
        "--port-file",
        help="write the bound port here once ready (how the fleet "
        "supervisor learns an ephemeral --port 0 assignment)",
    )
    args = p.parse_args(argv)

    cfg = ServeConfig()
    if args.config:
        with open(args.config) as f:
            cfg = ServeConfig.from_json(f.read())
    overrides = {
        k: v
        for k, v in
        (("workdir", args.workdir), ("host", args.host), ("port", args.port))
        if v is not None
    }
    if overrides:
        cfg = cfg.replace(**overrides)

    from ddlpc_tpu.train.observability import MetricsLogger

    engine = InferenceEngine.from_workdir(
        cfg.workdir,
        max_bucket=cfg.max_batch,
        quantize=cfg.quantize,
        quantize_activations=cfg.quantize_activations,
    )
    engine.warmup()  # compile every bucket before declaring ready
    metrics_dir = cfg.metrics_dir or cfg.workdir
    os.makedirs(metrics_dir, exist_ok=True)
    logger = MetricsLogger(metrics_dir, basename="serve_metrics")
    frontend = ServingFrontend(engine, cfg, logger=logger)
    server = make_server(frontend, cfg.host, cfg.port)
    if args.port_file:
        # Written AFTER warmup + bind: first contact never pays a compile,
        # and the file's very existence means "this port answers".
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(server.server_address[1]))
        os.replace(tmp, args.port_file)

    def _shutdown(signum, frame):
        # Stop accepting; the post-loop drain below finishes in-flight
        # work, flushes metrics, and exits 0 — never a dropped request.
        frontend.draining = True
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    print(
        f"serving {cfg.workdir} on http://{cfg.host}:{server.server_address[1]}"
        f" (tile {engine.tile}, max_batch {cfg.max_batch})",
        flush=True,
    )
    try:
        server.serve_forever()
    finally:
        drain_and_close(server, frontend, timeout_s=cfg.drain_timeout_s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
