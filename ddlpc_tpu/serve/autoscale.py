"""SLO-driven autoscaler: the reaction layer over the replica fleet.

The observability stack (PR 12) tells the fleet when it is burning
error budget; nothing acted on the signal — replica count was fixed at
`ReplicaSupervisor` launch.  This module closes the loop: a policy
thread reads the router's live view of the fleet (SLO burn rate,
per-priority queue depths, continuous-batcher slot busy fractions,
breaker states) and drives the supervisor's runtime
``scale_up`` / ``scale_down``.

Policy shape — deliberately boring hysteresis, not a controller:

- **Scale up** when ANY pressure signal crosses its high-water mark:
  interactive fast-window burn rate ≥ ``autoscale_burn_threshold``,
  mean interactive queue depth across ready replicas ≥
  ``autoscale_queue_depth_high``, or max replica slot-busy fraction ≥
  ``autoscale_slot_busy_high``.  One replica per decision; scale-up
  races warmup (the supervisor's readiness stays port-file + /healthz,
  so the new replica takes no traffic until it has compiled).
- **Scale down** only when EVERY signal is below its low-water mark
  (burn under 1.0 — spending inside budget — plus the ``*_low``
  thresholds).  The victim prefers a breaker-open replica (it is
  already taking no traffic), then an unhealthy one, then the
  highest-index ready replica (LIFO keeps the original fleet shape).
- **Never flaps**: ``autoscale_cooldown_s`` must elapse between
  actions, and ``autoscale_min_replicas`` / ``autoscale_max_replicas``
  bound the fleet absolutely.

Every decision AND every suppressed decision is a flat
``kind="autoscale"`` JSONL record carrying the triggering signal
values, so a scaling timeline is reconstructible from the stream alone.
Quiet holds (no pressure either way) emit nothing.

The module is jax-free (stdlib only; `analysis/tiers.py` host tier) and
fully injectable: the router and supervisor are duck-typed and the
clock is a parameter, so the policy is unit-testable with fakes and no
sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ddlpc_tpu.config import FleetConfig

_INTERACTIVE = "interactive"


class AutoscaleMetrics:
    """Registry wiring for the autoscaler (optional, like RouterMetrics)."""

    def __init__(self, registry=None):
        self._reg = {}
        if registry is not None:
            self._reg = {
                "decisions": registry.counter(
                    "ddlpc_autoscale_decisions_total",
                    "autoscaler decisions by action (including suppressions)",
                    labelnames=("action",),
                ),
                "target": registry.gauge(
                    "ddlpc_autoscale_replicas_target",
                    "replica count the autoscaler is currently steering to",
                ),
            }

    def record(self, action: str, target: int) -> None:
        if self._reg:
            self._reg["decisions"].inc(action=action)
            self._reg["target"].set(float(target))


class Autoscaler:
    """Threshold policy loop over a router (signals) + supervisor (actuation).

    ``router`` needs ``.slo.burn_rate(priority, window_s)`` and
    ``.replica_status()``; ``supervisor`` needs ``.replica_count()``,
    ``.scale_up() -> name`` and ``.scale_down(name) -> bool``.  Tests
    inject fakes for all three plus ``clock``.
    """

    def __init__(
        self,
        cfg: FleetConfig,
        router,
        supervisor,
        logger=None,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        self.router = router
        self.supervisor = supervisor
        self.logger = logger
        self.metrics = AutoscaleMetrics(registry)
        self._clock = clock
        self._last_action_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal gathering ---------------------------------------------------

    def _signals(self) -> Dict[str, float]:
        cfg = self.cfg
        try:
            burn = float(
                self.router.slo.burn_rate(_INTERACTIVE, cfg.slo_fast_window_s)
            )
        except Exception:
            burn = 0.0  # no SLO tracker (slo_enabled=False) → never a trigger
        statuses = self.router.replica_status()
        ready = [s for s in statuses if s.get("ready") and s.get("healthy")]
        queues = [float(s.get("queue_depth_interactive") or 0) for s in ready]
        busy = [
            float(s["slot_busy"]) for s in ready
            if s.get("slot_busy") is not None
        ]
        return {
            "burn_rate": burn,
            "queue_depth": (sum(queues) / len(queues)) if queues else 0.0,
            "slot_busy": max(busy) if busy else 0.0,
            "ready_replicas": float(len(ready)),
        }

    def _pick_victim(self) -> Optional[str]:
        """Scale-down victim: breaker-open first, then unhealthy, then the
        highest-named ready replica.  Draining replicas are already on
        their way out — never double-select one."""
        statuses: List[Dict[str, object]] = self.router.replica_status()
        candidates = [s for s in statuses if not s.get("draining")]
        if not candidates:
            return None

        def rank(s: Dict[str, object]):
            breaker_open = 0 if s.get("breaker") == "open" else 1
            unhealthy = 0 if not s.get("healthy") else 1
            return (breaker_open, unhealthy, _neg_name_key(str(s["name"])))

        return str(sorted(candidates, key=rank)[0]["name"])

    # -- the policy ---------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> Optional[str]:
        """One policy pass; returns the action taken/suppressed, or None
        on a quiet hold."""
        cfg = self.cfg
        now = self._clock() if now is None else now
        sig = self._signals()
        count = int(self.supervisor.replica_count())

        up_reasons = []
        if sig["burn_rate"] >= cfg.autoscale_burn_threshold:
            up_reasons.append("burn_rate")
        if sig["queue_depth"] >= cfg.autoscale_queue_depth_high:
            up_reasons.append("queue_depth")
        if sig["slot_busy"] >= cfg.autoscale_slot_busy_high:
            up_reasons.append("slot_busy")

        # A collapsed fleet reads exactly like an idle one — zero ready
        # replicas means zero queue depth and zero slot busy — so scale-
        # down additionally requires at least one ready replica to be
        # REPORTING those low signals, or the policy would retire
        # capacity in the middle of an outage.
        down_ok = (
            sig["ready_replicas"] > 0
            and sig["burn_rate"] < 1.0
            and sig["queue_depth"] <= cfg.autoscale_queue_depth_low
            and sig["slot_busy"] <= cfg.autoscale_slot_busy_low
        )

        cooling = (
            self._last_action_at is not None
            and (now - self._last_action_at) < cfg.autoscale_cooldown_s
        )

        if count < cfg.autoscale_min_replicas:
            # below the floor (e.g. a replica gave up): restore it even
            # during cooldown — the bound outranks flap damping.
            name = self.supervisor.scale_up()
            self._last_action_at = now
            return self._record(
                "scale_up", sig, count, count + 1, reason="below_min",
                replica=name,
            )

        if up_reasons:
            reason = ",".join(up_reasons)
            if count >= cfg.autoscale_max_replicas:
                return self._record(
                    "suppressed_max", sig, count, count, reason=reason
                )
            if cooling:
                return self._record(
                    "suppressed_cooldown", sig, count, count, reason=reason
                )
            name = self.supervisor.scale_up()
            self._last_action_at = now
            return self._record(
                "scale_up", sig, count, count + 1, reason=reason,
                replica=name,
            )

        if down_ok and count > cfg.autoscale_min_replicas:
            if cooling:
                return self._record(
                    "suppressed_cooldown", sig, count, count, reason="idle"
                )
            victim = self._pick_victim()
            if victim is None:
                return None
            if not self.supervisor.scale_down(victim):
                return None
            self._last_action_at = now
            return self._record(
                "scale_down", sig, count, count - 1, reason="idle",
                replica=victim,
            )

        if down_ok and count == cfg.autoscale_min_replicas and count > 0:
            # idle but pinned at the floor: stay quiet (this is the
            # steady state, not a decision worth a record).
            return None
        return None

    def _record(
        self,
        action: str,
        sig: Dict[str, float],
        replicas: int,
        target: int,
        reason: str,
        replica: Optional[str] = None,
    ) -> str:
        self.metrics.record(action, target)
        if self.logger is not None:
            rec: Dict[str, object] = {
                "kind": "autoscale",
                "action": action,
                "reason": reason,
                "replicas": replicas,
                "replicas_target": target,
            }
            rec.update(sig)
            if replica is not None:
                rec["replica"] = replica
            self.logger.log(rec)
        return action

    # -- background loop ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.autoscale_interval_s):
            try:
                self.evaluate()
            except Exception:
                # policy errors must never take down the fleet process;
                # the next tick retries with fresh signals.
                pass

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def _neg_name_key(name: str):
    """Sort key that puts the HIGHEST replica index first (LIFO victim
    order) while staying total for arbitrary names."""
    digits = "".join(c for c in name if c.isdigit())
    idx = int(digits) if digits else -1
    return (-idx, name)
