"""Tile datasets: directory-of-tiles readers + synthetic generator.

Reference parity (кластер.py:660-674, `load_files`): scan one directory; every
``.npy`` file is a label mask, every other file is an image read with imageio;
stack to numpy; the last ``test_split`` samples become the held-out split
(which the reference computes and then never uses, SURVEY §3.3 — here it feeds
the mIoU eval).  Preprocessing parity (кластер.py:737-742): images → float32
/255; labels → int.  Layout difference (deliberate, TPU-first): NHWC, not the
reference's NCHW swapaxes dance.

The synthetic generator produces Vaihingen-like tiles (smooth class regions +
class-correlated color noise) so tests and benchmarks run without the ISPRS
download; it is shape- and dtype-identical to the disk reader.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ddlpc_tpu.config import DataConfig

# Known dataset geometries (BASELINE.json configs).  H, W, channels, classes.
DATASET_SPECS = {
    "vaihingen": dict(image_size=(512, 512), channels=3, num_classes=6),
    "potsdam": dict(image_size=(512, 512), channels=3, num_classes=6),
    "cityscapes": dict(image_size=(512, 1024), channels=3, num_classes=19),
    "synthetic": dict(image_size=(512, 512), channels=3, num_classes=6),
    "synthetic_hard": dict(image_size=(512, 512), channels=3, num_classes=6),
}


class TileDataset:
    """In-RAM array-backed dataset of (image [H,W,C] float32, label [H,W] int32).

    Mirrors the reference's eager load-everything approach (кластер.py:660-674)
    — appropriate for ISPRS-scale corpora (~hundreds of tiles) — but behind an
    interface the sharded loader can index lazily.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray):
        if images.ndim != 4:
            raise ValueError(f"images must be [N,H,W,C], got {images.shape}")
        if labels.shape != images.shape[:3]:
            raise ValueError(
                f"labels {labels.shape} do not match images {images.shape[:3]}"
            )
        self.images = np.ascontiguousarray(images, np.float32)
        self.labels = np.ascontiguousarray(labels, np.int32)

    def __len__(self) -> int:
        return self.images.shape[0]

    def __getitem__(self, idx) -> Tuple[np.ndarray, np.ndarray]:
        return self.images[idx], self.labels[idx]

    def gather(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Materialize (images [n,H,W,C], labels [n,H,W]) for an index array.

        The loader's only data access point — crop-sampling datasets
        (:class:`CropDataset`) override it to materialize tiles on demand.
        """
        return self.images[indices], self.labels[indices]

    def gather_into(
        self, indices: np.ndarray, img_out: np.ndarray, lab_out: np.ndarray
    ) -> None:
        """Gather directly into caller-owned same-dtype buffers (the
        loader's buffer-ring path) — one copy instead of allocate+copy.

        Bounds are checked up front and ``np.take`` runs with
        ``mode='clip'``: numpy documents ``mode='raise'`` as ALWAYS
        buffered (a hidden super-batch-sized temporary plus a second
        copy — exactly what this method exists to avoid)."""
        idx = np.asarray(indices)
        if len(idx) and (idx.min() < 0 or idx.max() >= len(self.images)):
            raise IndexError(
                f"gather index out of range for dataset of "
                f"{len(self.images)} tiles"
            )
        np.take(self.images, idx, axis=0, mode="clip", out=img_out.reshape(
            len(idx), *self.images.shape[1:]
        ))
        np.take(self.labels, idx, axis=0, mode="clip", out=lab_out.reshape(
            len(idx), *self.labels.shape[1:]
        ))

    def set_epoch(self, epoch: int) -> None:
        """Hook for epoch-dependent sampling (no-op for fixed tiles)."""

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return tuple(self.images.shape[1:])  # type: ignore[return-value]


def _finish_image(
    img: np.ndarray,
    image_size: Optional[Tuple[int, int]],
    channels: int,
    normalize: bool,
) -> np.ndarray:
    """Post-decode pipeline shared by every image source (file decode and
    array tiles): ndim fixup, channel repeat/truncate, crop/zero-pad to
    ``image_size``, float32, /255 — ONE implementation so png and npy
    forms of the same source cannot drift."""
    if img.ndim == 2:
        img = img[..., None]
    if img.shape[-1] < channels:
        img = np.repeat(img[..., :1], channels, axis=-1)
    elif img.shape[-1] > channels:
        img = img[..., :channels]
    if image_size is not None:
        h, w = image_size
        img = img[:h, :w]
        if img.shape[0] < h or img.shape[1] < w:
            pad = ((0, h - img.shape[0]), (0, w - img.shape[1]), (0, 0))
            img = np.pad(img, pad)
    img = img.astype(np.float32)
    if normalize:
        img /= 255.0  # кластер.py:737
    return img


def load_image_file(
    path: str,
    image_size: Optional[Tuple[int, int]],
    channels: int = 3,
    normalize: bool = True,
) -> np.ndarray:
    """One image file → [H, W, channels] float array.

    ``image_size`` set: crops larger inputs (the reference's ``[:512,:512]``,
    кластер.py:822) and zero-pads smaller ones to exactly that size;
    ``image_size=None``: native size.  Repeats grayscale / drops alpha to
    reach ``channels``.  Shared by the tile reader, the scene reader, and
    the predict CLI so their preprocessing cannot drift."""
    import imageio.v2 as imageio

    return _finish_image(
        np.asarray(imageio.imread(path)), image_size, channels, normalize
    )


class CropDataset:
    """Random-crop view over arbitrarily-sized scenes.

    The reference's worker path opens aerial scenes of arbitrary size and
    crops 512×512 from each (кластер.py:817-823, one fixed top-left crop);
    this is the many-crops generalization that turns a directory of large
    scenes into as many training tiles as the batch arithmetic needs.

    ``len(ds)`` is ``crops_per_epoch``; crop positions are a pure function of
    (seed, epoch, index), so every process computing the same epoch sees the
    same global crop plan — exactly the property the sharded loader's
    shared-permutation sampling relies on (loader.py).  Scenes are sampled
    proportionally to their croppable area.

    Scene dtype is the normalization contract: **uint8 scenes are raw
    images** (the ``load_scene_dir(mmap=True)`` format) and gather()
    normalizes each crop with the same ``astype(float32)/255`` the eager
    loader applies; float scenes are taken as already normalized.  Callers
    holding uint8 data that is NOT 0-255 imagery must convert to float
    themselves.
    """

    def __init__(
        self,
        scenes: "list[Tuple[np.ndarray, np.ndarray]]",
        crop_size: Tuple[int, int],
        crops_per_epoch: int,
        seed: int = 0,
    ):
        if not scenes:
            raise ValueError("CropDataset needs at least one scene")
        ch, cw = crop_size
        self.scenes = []
        for i, (img, lab) in enumerate(scenes):
            if img.shape[:2] != lab.shape[:2]:
                raise ValueError(
                    f"scene {i}: image {img.shape[:2]} != label {lab.shape[:2]}"
                )
            # int32 before the -1 pad (uint8 would wrap void to 255).
            # np.asarray on an already-int32 memory map is a no-copy view,
            # so mmap scenes (load_scene_dir(mmap=True)) stay on disk.
            lab = np.asarray(lab, np.int32)
            if img.shape[0] < ch or img.shape[1] < cw:
                # Pad undersized scenes up to one crop (reference pads
                # nothing but also never checks; failing silently
                # mislabels).  Labels pad with void (-1), not class 0.
                pad_h, pad_w = max(ch - img.shape[0], 0), max(cw - img.shape[1], 0)
                img = np.pad(img, ((0, pad_h), (0, pad_w), (0, 0)))
                lab = np.pad(lab, ((0, pad_h), (0, pad_w)), constant_values=-1)
            # uint8 images (the mmap format) are kept as-is — gather()
            # normalizes per crop; anything else is materialized float32.
            if img.dtype != np.uint8:
                img = np.ascontiguousarray(img, np.float32)
            self.scenes.append((img, lab))
        self.crop_size = (ch, cw)
        self.crops_per_epoch = int(crops_per_epoch)
        if self.crops_per_epoch <= 0:
            raise ValueError(f"crops_per_epoch must be > 0, got {crops_per_epoch}")
        self.seed = seed
        areas = np.array(
            [
                (img.shape[0] - ch + 1) * (img.shape[1] - cw + 1)
                for img, _ in self.scenes
            ],
            np.float64,
        )
        self._scene_probs = areas / areas.sum()
        self._epoch = 0
        self._plan: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return self.crops_per_epoch

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        self._plan = None
        # Build eagerly: concurrent gather() calls from a multi-worker
        # loader would otherwise each recompute the plan (deterministic, so
        # content stays correct — but `workers` duplicate Python loops burn
        # the cores the pool exists to recruit).
        self._crop_plan()

    def _crop_plan(self) -> np.ndarray:
        """[crops_per_epoch, 3] (scene, y0, x0), deterministic per epoch."""
        if self._plan is None:
            rng = np.random.default_rng((self.seed, self._epoch))
            ch, cw = self.crop_size
            scene_ids = rng.choice(
                len(self.scenes), size=self.crops_per_epoch, p=self._scene_probs
            )
            ys = np.empty(self.crops_per_epoch, np.int64)
            xs = np.empty(self.crops_per_epoch, np.int64)
            for i, s in enumerate(scene_ids):
                img, _ = self.scenes[s]
                ys[i] = rng.integers(0, img.shape[0] - ch + 1)
                xs[i] = rng.integers(0, img.shape[1] - cw + 1)
            self._plan = np.stack([scene_ids, ys, xs], axis=1)
        return self._plan

    def gather(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        ch, cw = self.crop_size
        n = len(indices)
        c = self.scenes[0][0].shape[-1]
        imgs = np.empty((n, ch, cw, c), np.float32)
        labs = np.empty((n, ch, cw), np.int32)
        self.gather_into(indices, imgs, labs)
        return imgs, labs

    def gather_into(
        self, indices: np.ndarray, img_out: np.ndarray, lab_out: np.ndarray
    ) -> None:
        """Crop straight into caller-owned fp32/int32 buffers (the loader's
        buffer-ring path); ``gather`` is this plus the allocation."""
        plan = self._crop_plan()
        ch, cw = self.crop_size
        imgs = img_out.reshape(len(indices), *self.image_shape)
        labs = lab_out.reshape(len(indices), ch, cw)
        for out, idx in enumerate(np.asarray(indices, np.int64)):
            s, y0, x0 = plan[idx]
            img, lab = self.scenes[s]
            imgs[out] = img[y0 : y0 + ch, x0 : x0 + cw]
            if img.dtype == np.uint8:
                # mmap format: normalize per crop — same astype(f32)/255 as
                # load_image_file, so eager and mmap crops are bit-identical.
                imgs[out] /= 255.0
            labs[out] = lab[y0 : y0 + ch, x0 : x0 + cw]

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return (*self.crop_size, self.scenes[0][0].shape[-1])


class DihedralAugment:
    """Epoch-deterministic dihedral-group augmentation wrapper.

    Aerial tiles are orientation-free, so the standard augmentation is the
    8-element dihedral group (4 rotations × optional flip) applied jointly
    to image and mask.  The reference trains with no augmentation at all;
    this is opt-in (``DataConfig.augment``).  The transform for (epoch,
    index) is a pure function of the seed, so every process computing the
    same epoch applies identical augmentations — the property the sharded
    loader's shared permutation requires.
    """

    def __init__(self, ds, seed: int = 0):
        self.ds = ds
        self.seed = seed
        self._epoch = 0
        self._ks: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.ds)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        self._ks = None
        self.ds.set_epoch(epoch)
        self._epoch_ks()  # eager, same rationale as CropDataset.set_epoch

    @property
    def image_shape(self):
        h, w, c = self.ds.image_shape
        if h != w:
            raise ValueError(
                f"dihedral augmentation needs square tiles, got {(h, w)} "
                f"(90° rotations change the shape otherwise)"
            )
        return (h, w, c)

    def _epoch_ks(self) -> np.ndarray:
        """One transform draw per dataset index per epoch (not per gather
        position), so the same tile gets the same transform wherever it
        lands in the epoch; cached like CropDataset._crop_plan."""
        if self._ks is None:
            rng = np.random.default_rng((self.seed, self._epoch, 0xD1))
            self._ks = rng.integers(0, 8, size=len(self.ds))
        return self._ks

    def gather(self, indices: np.ndarray):
        self.image_shape  # square-tile validation
        # Both underlying gather()s return freshly-allocated arrays, so
        # in-place transformation is safe without a defensive copy.
        imgs, labs = self.ds.gather(indices)
        ks = self._epoch_ks()
        for out, idx in enumerate(np.asarray(indices, np.int64)):
            k = ks[idx]
            rot, flip = int(k % 4), bool(k >= 4)
            img, lab = imgs[out], labs[out]
            if rot:
                img = np.rot90(img, rot, axes=(0, 1))
                lab = np.rot90(lab, rot, axes=(0, 1))
            if flip:
                img = img[:, ::-1]
                lab = lab[:, ::-1]
            imgs[out] = img
            labs[out] = lab
        return imgs, labs


def gather_into(
    ds, indices: np.ndarray, img_out: np.ndarray, lab_out: np.ndarray
) -> None:
    """Gather ``ds[indices]`` into caller-owned fp32/int32 buffers.

    Dispatches to the dataset's own ``gather_into`` (TileDataset: one
    ``np.take`` copy; Crop/LazyTileDataset: materialize straight into the
    destination) and falls back to gather-then-copy for wrappers that
    transform tiles after materialization (:class:`DihedralAugment`).  The
    loader's buffer ring (data/loader.py) is the caller — this is what
    makes a steady-state epoch allocation-free on the host."""
    fn = getattr(ds, "gather_into", None)
    if fn is not None:
        fn(indices, img_out, lab_out)
        return
    imgs, labs = ds.gather(indices)
    img_out.reshape(imgs.shape)[...] = imgs
    lab_out.reshape(labs.shape)[...] = labs


def grid_tiles(
    scenes: "list[Tuple[np.ndarray, np.ndarray]]",
    tile_size: Tuple[int, int],
    max_tiles: Optional[int] = None,
) -> TileDataset:
    """Deterministic non-overlapping grid tiling of scenes → TileDataset.

    The fixed-tile counterpart of :class:`CropDataset` for held-out
    evaluation: mIoU must be computed on the same tiles every epoch.
    Same dtype contract as CropDataset: uint8 scenes are raw images and
    get the eager loader's ``astype(float32)/255``; float scenes are
    taken as already normalized.
    """
    th, tw = tile_size
    images, labels = [], []
    for img, lab in scenes:
        for y in range(0, max(img.shape[0] - th, 0) + 1, th):
            for x in range(0, max(img.shape[1] - tw, 0) + 1, tw):
                tile_img = img[y : y + th, x : x + tw]
                tile_lab = lab[y : y + th, x : x + tw]
                if tile_img.shape[:2] != (th, tw):
                    continue
                t = np.asarray(tile_img, np.float32)
                if tile_img.dtype == np.uint8:
                    t /= 255.0  # mmap scenes are raw uint8 (load_scene_dir)
                images.append(t)
                labels.append(np.asarray(tile_lab, np.int32))
                if max_tiles is not None and len(images) >= max_tiles:
                    break
            else:
                continue
            break
        if max_tiles is not None and len(images) >= max_tiles:
            break
    if not images:
        raise ValueError(f"no {tile_size} tiles fit in any scene")
    return TileDataset(np.stack(images), np.stack(labels))


def load_scene_dir(
    path: str, channels: int = 3, normalize: bool = True, mmap: bool = False
) -> "list[Tuple[np.ndarray, np.ndarray]]":
    """Directory of images + ``.npy`` masks at native size → scene list.

    Pairing is strict: image and mask must share a filename stem (modulo
    ``_mask``/``_label``/``_gt`` suffixes); unmatched files raise.

    ``mmap=True`` memory-maps every array instead of loading it: resident
    memory stays at the pages actually cropped, which is what makes
    Potsdam-scale corpora (~25 GB eager) feasible on ordinary hosts — the
    documented limit of the reference's eager design (кластер.py:660-674,
    docs/PERF.md "Reference-scale scene pipeline").  Requires array-format
    images (``<stem>_img.npy``, written by ``prepare_isprs.py --format
    npy``); images stay uint8 and consumers (:class:`CropDataset`,
    :func:`grid_tiles`) normalize per crop — bit-identical to the eager
    ``float32/255`` path.
    """
    if mmap and not normalize:
        raise ValueError(
            "mmap=True keeps scenes uint8 and consumers normalize per crop "
            "— normalize=False cannot be honored; load eagerly instead"
        )
    img_by_stem, npy_by_stem = _paired_files(path)
    scenes = []
    for s in sorted(img_by_stem):
        img_path = img_by_stem[s]
        if img_path.endswith(".npy"):
            if mmap:
                # Keep the uint8 memory map untouched: consumers normalize
                # per crop, and any repair (channel repeat, astype) would
                # materialize exactly what mmap exists to avoid — validate
                # strictly instead.
                img = np.load(img_path, mmap_mode="r")
                if img.ndim != 3 or img.shape[-1] != channels:
                    raise ValueError(
                        f"{img_path}: mmap images must be [H, W, "
                        f"{channels}], got shape {img.shape}"
                    )
                if img.dtype != np.uint8:
                    raise ValueError(
                        f"{img_path}: mmap images must be uint8 (the "
                        f"prepare_* converters write uint8; other dtypes "
                        f"would be silently materialized and mis-scaled "
                        f"downstream), got {img.dtype}"
                    )
            else:
                # Eager array read.  npy scenes are converter-controlled
                # (unlike decoded PNGs), so a channel mismatch is a data
                # error in BOTH modes — validate like the mmap branch,
                # then share the post-decode pipeline with file decode.
                img = np.load(img_path)
                if img.ndim != 3 or img.shape[-1] != channels:
                    raise ValueError(
                        f"{img_path}: array images must be [H, W, "
                        f"{channels}], got shape {img.shape}"
                    )
                if img.dtype != np.uint8:
                    # Same contract as the mmap branch and _read_tile: the
                    # prepare_* converters write uint8, and _finish_image
                    # divides by 255 — an already-float scene would be
                    # silently normalized TWICE (ADVICE r5).
                    raise ValueError(
                        f"{img_path}: array images must be uint8 (float "
                        f"scenes would be /255-normalized twice), got "
                        f"{img.dtype}"
                    )
                img = _finish_image(img, None, channels, normalize)
        elif mmap:
            raise ValueError(
                f"mmap=True needs array-format images (<stem>_img.npy), "
                f"got {img_path}; re-run scripts/prepare_isprs.py with "
                f"--format npy"
            )
        else:
            img = load_image_file(
                img_path, None, channels=channels, normalize=normalize
            )
        lab = np.load(npy_by_stem[s], mmap_mode="r" if mmap else None)
        if not mmap:
            lab = lab.astype(np.int32)
        elif lab.dtype != np.int32:
            raise ValueError(
                f"{npy_by_stem[s]}: mmap masks must be int32 (the "
                f"prepare_* converters write int32), got {lab.dtype}"
            )
        scenes.append((img, lab))
    return scenes


LABEL_SUFFIXES = ("_mask", "_label", "_labels", "_gt", "_noBoundary", "_RGB")


def file_stem(name: str, suffixes: Tuple[str, ...] = LABEL_SUFFIXES) -> str:
    """Filename → pairing stem: drop the extension, then strip label/image
    suffixes repeatedly (handles nested forms like ``_label_noBoundary``).
    One shared implementation so converters (scripts/prepare_isprs.py) and
    loaders can never disagree about which files pair."""
    base = os.path.basename(name)
    base = base[: base.rindex(".")] if "." in base else base
    stripped = True
    while stripped:
        stripped = False
        for suffix in suffixes:
            if base.endswith(suffix):
                base = base.removesuffix(suffix)
                stripped = True
    return base


def _paired_files(path: str) -> Tuple[dict, dict]:
    """{stem: image_path}, {stem: npy_path} with strict 1:1 stem matching."""

    stem = file_stem

    img_by_stem: dict = {}
    npy_by_stem: dict = {}
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if not os.path.isfile(full):
            continue
        # <stem>_img.npy is an IMAGE stored as a (mmap-able) array, not a
        # mask — route it to the image table despite the .npy extension,
        # stripping only the _img marker (kept out of LABEL_SUFFIXES so
        # ordinary files whose names end in _img keep their stems).
        if name.endswith("_img.npy"):
            table = img_by_stem
            # Re-attach an extension before file_stem so dotted stems
            # ("scene.v2_img.npy") don't get a second extension-strip.
            s = stem(name[: -len("_img.npy")] + ".npy")
        elif name.endswith(".npy"):
            table = npy_by_stem
            s = stem(name)
        else:
            table = img_by_stem
            s = stem(name)
        if s in table:
            raise ValueError(
                f"{path}: duplicate stem {s!r} ({table[s]} vs {full}) — "
                f"cannot pair images and masks unambiguously"
            )
        table[s] = full
    unmatched = sorted(
        set(img_by_stem) ^ set(npy_by_stem)
    )
    if not img_by_stem or unmatched:
        raise ValueError(
            f"{path}: every image needs a .npy mask with the same stem "
            f"(modulo _mask/_label/_gt suffixes; note *_img.npy files are "
            f"treated as ARRAY IMAGES, the prepare_* --format npy "
            f"convention); unmatched stems: "
            f"{unmatched[:10]}"
        )
    return img_by_stem, npy_by_stem


def _read_tile(
    img_path: str,
    npy_path: str,
    image_size: Optional[Tuple[int, int]],
    normalize: bool,
    channels: int = 3,
) -> Tuple[np.ndarray, np.ndarray]:
    """One (image, mask) pair from disk — the shared read used by both the
    eager and lazy tile datasets so their preprocessing cannot drift."""
    # int32 BEFORE padding: on a uint8 mask the -1 void pad would wrap
    # to 255 and silently train as the last class.
    lab = np.load(npy_path).astype(np.int32)
    size = tuple(image_size) if image_size is not None else lab.shape[:2]
    if img_path.endswith(".npy"):
        # Array-format tile (prepare_* --format npy): decode-free read,
        # then the same shared post-decode pipeline as file decode.
        img = np.load(img_path)
        if img.dtype != np.uint8:
            raise ValueError(
                f"{img_path}: array tiles must be uint8 raw imagery (the "
                f"prepare_* converters write uint8; a float array here "
                f"would be silently re-divided by 255), got {img.dtype}"
            )
        img = _finish_image(img, size, channels, normalize)
    else:
        img = load_image_file(
            img_path, size, channels=channels, normalize=normalize
        )
    lab = lab[: size[0], : size[1]]
    if lab.shape != size:
        # Void (-1), not class 0: padded pixels must not train or score
        # as the first class (the loss/metrics/confusion paths all
        # ignore -1).
        lab = np.pad(
            lab,
            ((0, size[0] - lab.shape[0]), (0, size[1] - lab.shape[1])),
            constant_values=-1,
        )
    return img, lab


class LazyTileDataset:
    """Fixed-tile dataset that reads tiles from disk per ``gather()``.

    The eager :func:`load_tile_dir` stacks every tile resident — ~20 GB for
    full Cityscapes at 512×1024 — which the reference's design forces
    (кластер.py:660-674) but nothing in this framework needs: the
    ShardedLoader's only access point is ``gather(indices)``, and its
    prefetch thread overlaps these reads with device compute.  Use
    ``prepare_*  --format npy`` tiles for decode-free reads.

    No ``.images``/``.labels`` arrays exist by construction; paths that
    need resident arrays (``DeviceCachedLoader``, prediction dumps) must
    use the eager loader — attribute access raises with that instruction.
    """

    def __init__(
        self,
        pairs: "list[Tuple[str, str]]",
        image_size: Optional[Tuple[int, int]] = None,
        normalize: bool = True,
        channels: int = 3,
    ):
        if not pairs:
            raise ValueError("LazyTileDataset needs at least one tile")
        self.pairs = list(pairs)
        self.image_size = tuple(image_size) if image_size else None
        self.normalize = normalize
        self.channels = channels
        img0, lab0 = _read_tile(
            *self.pairs[0], self.image_size, normalize, channels
        )
        self._shape = img0.shape

    def __len__(self) -> int:
        return len(self.pairs)

    def gather(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.asarray(indices, np.int64)
        imgs = np.empty((len(idx), *self._shape), np.float32)
        labs = np.empty((len(idx), *self._shape[:2]), np.int32)
        self.gather_into(idx, imgs, labs)
        return imgs, labs

    def gather_into(
        self, indices: np.ndarray, img_out: np.ndarray, lab_out: np.ndarray
    ) -> None:
        """Read tiles from disk straight into caller-owned fp32/int32
        buffers (the loader's buffer-ring path)."""
        idx = np.asarray(indices, np.int64)
        imgs = img_out.reshape(len(idx), *self._shape)
        labs = lab_out.reshape(len(idx), *self._shape[:2])
        for out, i in enumerate(idx):
            img, lab = _read_tile(
                *self.pairs[i], self.image_size, self.normalize, self.channels
            )
            if img.shape != self._shape:
                raise ValueError(
                    f"tile {self.pairs[i][0]}: shape {img.shape} != first "
                    f"tile {self._shape}; pass image_size to unify"
                )
            imgs[out] = img
            labs[out] = lab

    def set_epoch(self, epoch: int) -> None:
        """Fixed tiles: nothing epoch-dependent."""

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return self._shape  # type: ignore[return-value]

    def subset(self, start: int, stop: int) -> "LazyTileDataset":
        """File-list slice (train/test split without touching pixel data)."""
        ds = object.__new__(LazyTileDataset)
        ds.pairs = self.pairs[start:stop]
        if not ds.pairs:
            raise ValueError(f"empty subset [{start}:{stop}]")
        ds.image_size = self.image_size
        ds.normalize = self.normalize
        ds.channels = self.channels
        ds._shape = self._shape
        return ds

    def materialize(self) -> TileDataset:
        """Eager-load every tile (small splits, e.g. the eval holdout)."""
        imgs, labs = self.gather(np.arange(len(self)))
        return TileDataset(imgs, labs)

    def __getattr__(self, name):
        if name in ("images", "labels"):
            raise AttributeError(
                f"LazyTileDataset has no resident '{name}' array; use the "
                f"eager load_tile_dir (or .materialize()) for paths that "
                f"need whole-dataset arrays (device_cache, dumps)"
            )
        raise AttributeError(name)


def tile_dir_pairs(path: str) -> "list[Tuple[str, str]]":
    """Sorted (image_path, mask_path) pairs for a tile directory."""
    img_by_stem, npy_by_stem = _paired_files(path)
    return [(img_by_stem[s], npy_by_stem[s]) for s in sorted(img_by_stem)]


def load_tile_dir(
    path: str,
    image_size: Optional[Tuple[int, int]] = None,
    normalize: bool = True,
    lazy: bool = False,
) -> "TileDataset | LazyTileDataset":
    """Read one directory of image files + ``.npy`` masks (кластер.py:660-674).

    Pairing is strict by filename stem (modulo ``_mask``/``_label``/``_gt``
    suffixes) and raises on unmatched files — the reference pairs by
    directory-scan order, which silently mislabels every tile when the two
    kinds' sort orders diverge (e.g. unpadded ``tile_10`` vs ``tile_2``).
    Images are cropped/truncated to ``image_size`` the way the reference
    crops ``[:512, :512]`` (кластер.py:822).

    ``lazy=True`` returns a :class:`LazyTileDataset` that reads tiles per
    gather instead of stacking the whole directory resident — the
    full-Cityscapes-volume path (``DataConfig.lazy_tiles``).
    """
    pairs = tile_dir_pairs(path)
    if lazy:
        return LazyTileDataset(pairs, image_size, normalize)
    images, labels = [], []
    for img_path, npy_path in pairs:
        img, lab = _read_tile(img_path, npy_path, image_size, normalize)
        images.append(img)
        labels.append(lab)
    return TileDataset(np.stack(images), np.stack(labels).astype(np.int32))


def last_n_split_point(n: int, test_split: int) -> int:
    """Validated cut index for the last-N holdout (кластер.py:672-673) —
    one source of truth for both the eager and lazy split paths."""
    k = max(test_split, 0)
    if k >= n:
        raise ValueError(
            f"test_split={test_split} would leave no training tiles "
            f"(dataset has {n}); lower DataConfig.test_split or add data"
        )
    return n - k


def train_test_split(
    ds: TileDataset, test_split: int
) -> Tuple[TileDataset, TileDataset]:
    """Last-N holdout, reference behavior (кластер.py:672-673)."""
    cut = last_n_split_point(len(ds), test_split)
    return (
        TileDataset(ds.images[:cut], ds.labels[:cut]),
        TileDataset(ds.images[cut:], ds.labels[cut:]),
    )


def SyntheticTiles(
    num_tiles: int = 127,
    image_size: Tuple[int, int] = (512, 512),
    channels: int = 3,
    num_classes: int = 6,
    seed: int = 0,
) -> TileDataset:
    """Vaihingen-like synthetic tiles: blocky class regions, class-tinted pixels.

    Labels are piecewise-constant (low-res random class grid upsampled), so a
    segmentation net can genuinely learn from color — loss decreases and mIoU
    rises, which is what the e2e tests assert.
    """
    rng = np.random.default_rng(seed)
    h, w = image_size
    gh, gw = max(h // 32, 1), max(w // 32, 1)
    grid = rng.integers(0, num_classes, size=(num_tiles, gh, gw))
    # Ceil the upsample factor so the crop always has full h×w coverage even
    # when gh/gw do not divide h/w exactly.
    labels = np.repeat(np.repeat(grid, -(-h // gh), axis=1), -(-w // gw), axis=2)
    labels = labels[:, :h, :w].astype(np.int32)
    # One distinct color per class + noise.
    palette = rng.uniform(0.1, 0.9, size=(num_classes, channels)).astype(np.float32)
    images = palette[labels]  # [N,H,W,C]
    images += rng.normal(0.0, 0.05, size=images.shape).astype(np.float32)
    return TileDataset(np.clip(images, 0.0, 1.0), labels)


def _bilinear_up(a: np.ndarray, out_hw: Tuple[int, int]) -> np.ndarray:
    """Bilinear-upsample [N, gh, gw] → [N, H, W] (numpy, no scipy)."""
    n, gh, gw = a.shape
    h, w = out_hw
    y = np.clip((np.arange(h) + 0.5) * gh / h - 0.5, 0, gh - 1)
    x = np.clip((np.arange(w) + 0.5) * gw / w - 0.5, 0, gw - 1)
    y0 = np.floor(y).astype(np.int64)
    x0 = np.floor(x).astype(np.int64)
    y1 = np.minimum(y0 + 1, gh - 1)
    x1 = np.minimum(x0 + 1, gw - 1)
    wy = (y - y0)[None, :, None]
    wx = (x - x0)[None, None, :]
    return (
        a[:, y0][:, :, x0] * (1 - wy) * (1 - wx)
        + a[:, y1][:, :, x0] * wy * (1 - wx)
        + a[:, y0][:, :, x1] * (1 - wy) * wx
        + a[:, y1][:, :, x1] * wy * wx
    ).astype(np.float32)


def HardTiles(
    num_tiles: int = 127,
    image_size: Tuple[int, int] = (512, 512),
    channels: int = 3,
    num_classes: int = 6,
    seed: int = 0,
) -> TileDataset:
    """Non-saturating synthetic segmentation task (VERDICT r2 next #1).

    :func:`SyntheticTiles` is block-constant at ≥32 px, so every
    architecture/codec arm converges to mIoU ~1.0 and quality A/Bs lose
    discriminating power.  This generator puts structure *below* the
    granularity of coarse heads and makes classes imbalanced, so converged
    mIoU lands meaningfully under 1.0 and arms separate:

    - classes 0/1: large background blocks (64 px grid) — easy, balanced;
    - class 2: irregular blobs from a thresholded bilinear noise field
      (8 px lattice) — boundary-dense at a scale subpixel heads must track;
    - class 3: thin polylines, width 1–3 px (~1–2 % of pixels) — strictly
      sub-16-px structure, the acknowledged s2d×4 fine-boundary risk
      (docs/QUANTIZATION.md caveat);
    - class 4: small discs, radius 2–6 px (~1 % of pixels) — rare small
      objects, punished per-class by mIoU;
    - class 5: 4 px checkerboard texture patches — boundary density exactly
      at a factor-4 subpixel head's output granularity.

    Pixels get a per-class palette color modulated by a low-frequency
    multiplicative lighting field (×0.75–1.25) plus iid noise, so per-pixel
    color alone is not sufficient — context is required, and a per-pixel
    Bayes classifier would not reach IoU 1.0 either.  Same shapes/dtypes as
    the disk readers; the reference has no synthetic data at all (its quality
    evidence is eyeballed PNG dumps, кластер.py:785-790).
    """
    if num_classes < 6:
        raise ValueError(
            f"HardTiles defines 6 structural classes; got num_classes={num_classes}"
        )
    h, w = image_size
    if min(h, w) < 64:
        # Structure sizes are ABSOLUTE pixels (that is the point of the
        # task); the checkerboard/disc samplers need room for their patches.
        raise ValueError(
            f"HardTiles needs image_size >= 64 px per side, got {image_size}"
        )
    rng = np.random.default_rng(seed)
    BG_A, BG_B, BLOB, LINE, DISC, CHECKER = 0, 1, 2, 3, 4, 5

    # Backgrounds: 64 px blocks of class 0/1.
    gh, gw = max(h // 64, 1), max(w // 64, 1)
    grid = rng.integers(0, 2, size=(num_tiles, gh, gw))
    labels = np.repeat(np.repeat(grid, -(-h // gh), axis=1), -(-w // gw), axis=2)
    labels = labels[:, :h, :w].astype(np.int32)

    # Irregular blobs: thresholded bilinear noise on an 8 px lattice.
    field = _bilinear_up(
        rng.normal(size=(num_tiles, max(h // 8, 2), max(w // 8, 2))), (h, w)
    )
    labels[field > 0.9] = BLOB

    yy, xx = np.mgrid[0:h, 0:w]
    checker = ((yy // 4) + (xx // 4)) % 2 == 0  # 4 px checkerboard phase
    for i in range(num_tiles):
        # Checkerboard texture patches (before lines/discs so thin structure
        # stays on top).
        for _ in range(rng.integers(1, 3)):
            ph = int(rng.integers(48, min(161, h)))
            pw = int(rng.integers(48, min(161, w)))
            py = int(rng.integers(0, h - ph + 1))
            px = int(rng.integers(0, w - pw + 1))
            patch = labels[i, py : py + ph, px : px + pw]
            patch[checker[py : py + ph, px : px + pw]] = CHECKER
        # Thin polylines, width 1–3 px.
        for _ in range(8):
            p0 = rng.uniform(0, [h, w])
            p1 = rng.uniform(0, [h, w])
            width = int(rng.integers(1, 4))
            t = np.linspace(0.0, 1.0, 2 * max(h, w))[:, None]
            pts = np.round(p0 + t * (p1 - p0)).astype(np.int64)
            r = (width - 1) // 2
            for dy in range(-r, width - r):
                for dx in range(-r, width - r):
                    py = np.clip(pts[:, 0] + dy, 0, h - 1)
                    px = np.clip(pts[:, 1] + dx, 0, w - 1)
                    labels[i, py, px] = LINE
        # Small discs, radius 2–6 px.
        for _ in range(15):
            r = int(rng.integers(2, 7))
            cy = int(rng.integers(r, h - r))
            cx = int(rng.integers(r, w - r))
            dy, dx = np.mgrid[-r : r + 1, -r : r + 1]
            mask = dy * dy + dx * dx <= r * r
            patch = labels[i, cy - r : cy + r + 1, cx - r : cx + r + 1]
            patch[mask] = DISC

    palette = rng.uniform(0.15, 0.85, size=(num_classes, channels)).astype(
        np.float32
    )
    # Confusable class pairs: pull bulk-background B toward A, the
    # checkerboard toward background A, and discs toward lines, so the
    # lighting field + noise genuinely overlap their color distributions and
    # per-pixel color cannot solve the task (context must disambiguate).
    palette[BG_B] = 0.65 * palette[BG_A] + 0.35 * palette[BG_B]
    palette[CHECKER] = 0.6 * palette[BG_A] + 0.4 * palette[CHECKER]
    palette[DISC] = 0.6 * palette[LINE] + 0.4 * palette[DISC]
    images = palette[labels]  # [N,H,W,C]
    lighting = _bilinear_up(
        rng.uniform(0.75, 1.25, size=(num_tiles, max(h // 128, 2), max(w // 128, 2))),
        (h, w),
    )
    images *= lighting[..., None]
    images += rng.normal(0.0, 0.08, size=images.shape).astype(np.float32)
    return TileDataset(np.clip(images, 0.0, 1.0), labels)


SYNTHETIC_GENERATORS = {"synthetic": SyntheticTiles, "synthetic_hard": HardTiles}


def dataset_defaults(name: str, **overrides) -> DataConfig:
    """A DataConfig pre-filled with a known dataset's geometry
    (BASELINE.json configs: vaihingen/potsdam 512×512 6-class,
    cityscapes 512×1024 19-class)."""
    spec = DATASET_SPECS[name]
    kw = dict(
        dataset=name,
        image_size=spec["image_size"],
        num_classes=spec["num_classes"],
    )
    kw.update(overrides)
    return DataConfig(**kw)


def _synthetic_scenes(
    cfg: DataConfig, channels: int
) -> "list[Tuple[np.ndarray, np.ndarray]]":
    """A few large Vaihingen-like scenes (~3 crops on a side each) so crop
    mode is testable/benchmarkable without the ISPRS download."""
    h, w = cfg.image_size
    n_scenes = max(2, cfg.test_split_scenes + 1)
    big = SyntheticTiles(
        num_tiles=n_scenes,
        image_size=(h * 3, w * 3),
        channels=channels,
        num_classes=cfg.num_classes,
        seed=cfg.seed,
    )
    return [(big.images[i], big.labels[i]) for i in range(n_scenes)]


def build_dataset(cfg: DataConfig):
    """(train, test) pair from a DataConfig; synthetic when data_dir unset.

    Fixed-tile mode (``crops_per_epoch == 0``): the directory holds
    ready-made tiles; last ``test_split`` are held out (кластер.py:672-673).
    Crop mode (``crops_per_epoch > 0``): the directory holds full-size
    scenes; train is a :class:`CropDataset` drawing ``crops_per_epoch``
    random crops per epoch, test is a deterministic grid tiling of the last
    ``test_split_scenes`` scenes.

    ``cfg`` is authoritative; a mismatch with the named dataset's known
    geometry (DATASET_SPECS) gets a warning so e.g. dataset='cityscapes'
    with the default 6-class 512×512 config can't pass silently.  Use
    :func:`dataset_defaults` to start from the right geometry.
    """
    spec = DATASET_SPECS.get(cfg.dataset)
    if spec is not None and cfg.dataset != "synthetic":
        if (
            tuple(cfg.image_size) != spec["image_size"]
            or cfg.num_classes != spec["num_classes"]
        ):
            import warnings

            warnings.warn(
                f"DataConfig({cfg.dataset!r}) has image_size={cfg.image_size}, "
                f"num_classes={cfg.num_classes} but {cfg.dataset} is "
                f"{spec['image_size']}, {spec['num_classes']} classes; the "
                f"config wins — use dataset_defaults({cfg.dataset!r}) if "
                f"this is unintended",
                stacklevel=2,
            )
    channels = (spec or DATASET_SPECS["synthetic"])["channels"]
    if cfg.mmap_scenes and (not cfg.data_dir or cfg.crops_per_epoch <= 0):
        raise ValueError(
            "mmap_scenes needs crop mode over a scene directory "
            "(data_dir set and crops_per_epoch > 0); fixed-tile and "
            "synthetic datasets are loaded eagerly"
        )
    if cfg.lazy_tiles and cfg.crops_per_epoch > 0:
        raise ValueError(
            "lazy_tiles is a fixed-tile-mode option; crop mode over large "
            "scenes wants mmap_scenes instead"
        )
    if cfg.crops_per_epoch > 0:
        scenes = (
            load_scene_dir(cfg.data_dir, mmap=cfg.mmap_scenes)
            if cfg.data_dir
            else _synthetic_scenes(cfg, channels)
        )
        k = cfg.test_split_scenes
        if k < 0 or (k > 0 and k >= len(scenes)):
            raise ValueError(
                f"test_split_scenes={k} must leave at least one training "
                f"scene (directory has {len(scenes)})"
            )
        train_scenes = scenes[: len(scenes) - k] if k else scenes
        train = CropDataset(
            train_scenes,
            crop_size=tuple(cfg.image_size),
            crops_per_epoch=cfg.crops_per_epoch,
            seed=cfg.seed,
        )
        if cfg.augment:
            train = DihedralAugment(train, seed=cfg.seed)
        if k:
            test = grid_tiles(
                scenes[len(scenes) - k :],
                tuple(cfg.image_size),
                max_tiles=cfg.test_split or None,
            )
        else:
            test = TileDataset(
                np.zeros((0, *cfg.image_size, channels), np.float32),
                np.zeros((0, *cfg.image_size), np.int32),
            )
        return train, test
    if cfg.lazy_tiles:
        if not cfg.data_dir:
            raise ValueError(
                "lazy_tiles reads tiles from disk per gather — it needs "
                "data_dir (synthetic datasets are generated resident)"
            )
        lazy = load_tile_dir(
            cfg.data_dir, image_size=tuple(cfg.image_size), lazy=True
        )
        cut = last_n_split_point(len(lazy), cfg.test_split)
        train = lazy.subset(0, cut)
        # The holdout is small by design (reference: last 30 tiles) and the
        # eval/dump paths need resident arrays — materialize it.
        test = (
            lazy.subset(cut, len(lazy)).materialize()
            if cut < len(lazy) else
            TileDataset(
                np.zeros((0, *lazy.image_shape), np.float32),
                np.zeros((0, *lazy.image_shape[:2]), np.int32),
            )
        )
        if cfg.augment:
            train = DihedralAugment(train, seed=cfg.seed)
        return train, test
    if cfg.data_dir:
        ds = load_tile_dir(cfg.data_dir, image_size=tuple(cfg.image_size))
    else:
        generator = SYNTHETIC_GENERATORS.get(cfg.dataset, SyntheticTiles)
        ds = generator(
            num_tiles=cfg.synthetic_len,
            image_size=tuple(cfg.image_size),
            channels=channels,
            num_classes=cfg.num_classes,
            seed=cfg.seed,
        )
    train, test = train_test_split(ds, cfg.test_split)
    if cfg.augment:
        train = DihedralAugment(train, seed=cfg.seed)
    return train, test
