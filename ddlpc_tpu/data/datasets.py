"""Tile datasets: directory-of-tiles readers + synthetic generator.

Reference parity (кластер.py:660-674, `load_files`): scan one directory; every
``.npy`` file is a label mask, every other file is an image read with imageio;
stack to numpy; the last ``test_split`` samples become the held-out split
(which the reference computes and then never uses, SURVEY §3.3 — here it feeds
the mIoU eval).  Preprocessing parity (кластер.py:737-742): images → float32
/255; labels → int.  Layout difference (deliberate, TPU-first): NHWC, not the
reference's NCHW swapaxes dance.

The synthetic generator produces Vaihingen-like tiles (smooth class regions +
class-correlated color noise) so tests and benchmarks run without the ISPRS
download; it is shape- and dtype-identical to the disk reader.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ddlpc_tpu.config import DataConfig

# Known dataset geometries (BASELINE.json configs).  H, W, channels, classes.
DATASET_SPECS = {
    "vaihingen": dict(image_size=(512, 512), channels=3, num_classes=6),
    "potsdam": dict(image_size=(512, 512), channels=3, num_classes=6),
    "cityscapes": dict(image_size=(512, 1024), channels=3, num_classes=19),
    "synthetic": dict(image_size=(512, 512), channels=3, num_classes=6),
}


class TileDataset:
    """In-RAM array-backed dataset of (image [H,W,C] float32, label [H,W] int32).

    Mirrors the reference's eager load-everything approach (кластер.py:660-674)
    — appropriate for ISPRS-scale corpora (~hundreds of tiles) — but behind an
    interface the sharded loader can index lazily.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray):
        if images.ndim != 4:
            raise ValueError(f"images must be [N,H,W,C], got {images.shape}")
        if labels.shape != images.shape[:3]:
            raise ValueError(
                f"labels {labels.shape} do not match images {images.shape[:3]}"
            )
        self.images = np.ascontiguousarray(images, np.float32)
        self.labels = np.ascontiguousarray(labels, np.int32)

    def __len__(self) -> int:
        return self.images.shape[0]

    def __getitem__(self, idx) -> Tuple[np.ndarray, np.ndarray]:
        return self.images[idx], self.labels[idx]

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return tuple(self.images.shape[1:])  # type: ignore[return-value]


def load_image_file(
    path: str,
    image_size: Tuple[int, int],
    channels: int = 3,
    normalize: bool = True,
) -> np.ndarray:
    """One image file → [H, W, channels] float array at exactly
    ``image_size``: crops larger inputs (the reference's ``[:512,:512]``,
    кластер.py:822), zero-pads smaller ones, repeats grayscale / drops alpha
    to reach ``channels``.  Shared by the dataset reader and the predict CLI
    so their preprocessing cannot drift."""
    import imageio.v2 as imageio

    img = np.asarray(imageio.imread(path))
    if img.ndim == 2:
        img = img[..., None]
    if img.shape[-1] < channels:
        img = np.repeat(img[..., :1], channels, axis=-1)
    elif img.shape[-1] > channels:
        img = img[..., :channels]
    h, w = image_size
    img = img[:h, :w]
    if img.shape[0] < h or img.shape[1] < w:
        pad = ((0, h - img.shape[0]), (0, w - img.shape[1]), (0, 0))
        img = np.pad(img, pad)
    img = img.astype(np.float32)
    if normalize:
        img /= 255.0  # кластер.py:737
    return img


def load_tile_dir(
    path: str,
    image_size: Optional[Tuple[int, int]] = None,
    normalize: bool = True,
) -> TileDataset:
    """Read one directory of image files + ``.npy`` masks (кластер.py:660-674).

    Pairing is by sorted order within each kind, exactly like the reference's
    single-pass directory scan (it relies on interleaved naming; sorting the
    two kinds independently is the robust version of the same contract).
    Images are center-cropped/truncated to ``image_size`` the way the
    reference crops ``[:512, :512]`` (кластер.py:822).
    """
    import imageio.v2 as imageio

    img_files, npy_files = [], []
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if not os.path.isfile(full):
            continue
        (npy_files if name.endswith(".npy") else img_files).append(full)
    if not img_files or len(img_files) != len(npy_files):
        raise ValueError(
            f"{path}: need equal numbers of image and .npy mask files, "
            f"got {len(img_files)} images / {len(npy_files)} masks"
        )
    # Sorted-order pairing relies on consistent naming; catch schemes whose
    # lexicographic orders diverge (e.g. zero-padded masks vs unpadded images)
    # before they silently mislabel every tile.
    def stem(f: str) -> str:
        base = os.path.basename(f)
        base = base[: base.rindex(".")] if "." in base else base
        for suffix in ("_mask", "_label", "_labels", "_gt"):
            base = base.removesuffix(suffix)
        return base

    mismatched = [
        (i, stem(a), stem(b))
        for i, (a, b) in enumerate(zip(img_files, npy_files))
        if stem(a) != stem(b)
        and not stem(b).startswith(stem(a))
        and not stem(a).startswith(stem(b))
    ]
    if mismatched:
        import warnings

        i, a, b = mismatched[0]
        warnings.warn(
            f"{path}: image/mask pairing is by sorted order and pair {i} has "
            f"unrelated stems ({a!r} vs {b!r}) — verify file naming",
            stacklevel=2,
        )
    images, labels = [], []
    for img_f, npy_f in zip(img_files, npy_files):
        lab = np.load(npy_f)
        size = tuple(image_size) if image_size is not None else lab.shape[:2]
        images.append(load_image_file(img_f, size, normalize=normalize))
        lab = lab[: size[0], : size[1]]
        if lab.shape != size:
            lab = np.pad(
                lab, ((0, size[0] - lab.shape[0]), (0, size[1] - lab.shape[1]))
            )
        labels.append(lab)
    return TileDataset(np.stack(images), np.stack(labels).astype(np.int32))


def train_test_split(
    ds: TileDataset, test_split: int
) -> Tuple[TileDataset, TileDataset]:
    """Last-N holdout, reference behavior (кластер.py:672-673)."""
    n = len(ds)
    k = max(test_split, 0)
    if k >= n:
        raise ValueError(
            f"test_split={test_split} would leave no training tiles "
            f"(dataset has {n}); lower DataConfig.test_split or add data"
        )
    cut = n - k
    return (
        TileDataset(ds.images[:cut], ds.labels[:cut]),
        TileDataset(ds.images[cut:], ds.labels[cut:]),
    )


def SyntheticTiles(
    num_tiles: int = 127,
    image_size: Tuple[int, int] = (512, 512),
    channels: int = 3,
    num_classes: int = 6,
    seed: int = 0,
) -> TileDataset:
    """Vaihingen-like synthetic tiles: blocky class regions, class-tinted pixels.

    Labels are piecewise-constant (low-res random class grid upsampled), so a
    segmentation net can genuinely learn from color — loss decreases and mIoU
    rises, which is what the e2e tests assert.
    """
    rng = np.random.default_rng(seed)
    h, w = image_size
    gh, gw = max(h // 32, 1), max(w // 32, 1)
    grid = rng.integers(0, num_classes, size=(num_tiles, gh, gw))
    # Ceil the upsample factor so the crop always has full h×w coverage even
    # when gh/gw do not divide h/w exactly.
    labels = np.repeat(np.repeat(grid, -(-h // gh), axis=1), -(-w // gw), axis=2)
    labels = labels[:, :h, :w].astype(np.int32)
    # One distinct color per class + noise.
    palette = rng.uniform(0.1, 0.9, size=(num_classes, channels)).astype(np.float32)
    images = palette[labels]  # [N,H,W,C]
    images += rng.normal(0.0, 0.05, size=images.shape).astype(np.float32)
    return TileDataset(np.clip(images, 0.0, 1.0), labels)


def dataset_defaults(name: str, **overrides) -> DataConfig:
    """A DataConfig pre-filled with a known dataset's geometry
    (BASELINE.json configs: vaihingen/potsdam 512×512 6-class,
    cityscapes 512×1024 19-class)."""
    spec = DATASET_SPECS[name]
    kw = dict(
        dataset=name,
        image_size=spec["image_size"],
        num_classes=spec["num_classes"],
    )
    kw.update(overrides)
    return DataConfig(**kw)


def build_dataset(cfg: DataConfig) -> Tuple[TileDataset, TileDataset]:
    """(train, test) pair from a DataConfig; synthetic when data_dir unset.

    ``cfg`` is authoritative; a mismatch with the named dataset's known
    geometry (DATASET_SPECS) gets a warning so e.g. dataset='cityscapes'
    with the default 6-class 512×512 config can't pass silently.  Use
    :func:`dataset_defaults` to start from the right geometry.
    """
    spec = DATASET_SPECS.get(cfg.dataset)
    if spec is not None and cfg.dataset != "synthetic":
        if (
            tuple(cfg.image_size) != spec["image_size"]
            or cfg.num_classes != spec["num_classes"]
        ):
            import warnings

            warnings.warn(
                f"DataConfig({cfg.dataset!r}) has image_size={cfg.image_size}, "
                f"num_classes={cfg.num_classes} but {cfg.dataset} is "
                f"{spec['image_size']}, {spec['num_classes']} classes; the "
                f"config wins — use dataset_defaults({cfg.dataset!r}) if "
                f"this is unintended",
                stacklevel=2,
            )
    if cfg.data_dir:
        ds = load_tile_dir(cfg.data_dir, image_size=tuple(cfg.image_size))
    else:
        channels = (spec or DATASET_SPECS["synthetic"])["channels"]
        ds = SyntheticTiles(
            num_tiles=cfg.synthetic_len,
            image_size=tuple(cfg.image_size),
            channels=channels,
            num_classes=cfg.num_classes,
            seed=cfg.seed,
        )
    return train_test_split(ds, cfg.test_split)
