"""Data layer: tile datasets, preprocessing, host-sharded batching.

The reference loads one directory of images + ``.npy`` masks eagerly into RAM
on *every* node and iterates the same 127 tiles in the same order on each
replica — no sharding at all (кластер.py:660-674,732,750; SURVEY §3.1).  This
package provides the corrected design: datasets that actually shard across
processes and mesh replicas, with a synthetic generator for tests/benchmarks.
"""

from ddlpc_tpu.data.datasets import (  # noqa: F401
    CropDataset,
    DihedralAugment,
    HardTiles,
    LazyTileDataset,
    SyntheticTiles,
    TileDataset,
    build_dataset,
    dataset_defaults,
    grid_tiles,
    load_scene_dir,
    load_tile_dir,
    train_test_split,
)
from ddlpc_tpu.data.loader import (  # noqa: F401
    DeviceCachedLoader,
    ShardedLoader,
    make_global_array,
)
