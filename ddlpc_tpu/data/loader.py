"""Host-sharded batching: numpy tiles → globally-sharded jax.Arrays.

This fixes the reference's central data defect: every replica there trains on
the *same* 127 tiles in the *same* order (its shuffle is computed then never
applied, кластер.py:722-723,750; SURVEY §3.1), so k replicas do k× redundant
work.  Here one global permutation (same seed on every process) is sliced
per-process, each host materializes only its slice, and
``jax.make_array_from_process_local_data`` assembles the global sharded batch
the compiled step consumes — the standard multi-host JAX input path, replacing
nothing-in-the-reference (it has no sampler at all).

Batch layout for the train step (parallel/train_step.py):
  images [A, B, H, W, C], labels [A, B, H, W]
A = sync_period micro-batches per optimizer step (reference
``frequency_sending_gradients``, кластер.py:685), B = global micro-batch
sharded over the mesh ``data`` axis (and H over ``space`` when used).
"""

from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.data.datasets import TileDataset


def _compact_cast(
    imgs: np.ndarray, labs: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """fp32/int32 → bf16/int8 (44% of the bytes), shared by BOTH transports
    so the wire form and the resident-cache form can never drift.  Labels
    must fit int8 with the −1 void sentinel."""
    if labs.min() < -1 or labs.max() > 127:
        raise ValueError(
            f"compact=True needs labels in [-1, 127] for int8, "
            f"got range [{labs.min()}, {labs.max()}]"
        )
    return imgs.astype(ml_dtypes.bfloat16), labs.astype(np.int8)


def make_global_array(
    local: np.ndarray, mesh: Mesh, spec: P
) -> jax.Array:
    """Assemble a global sharded array from this process's local shard."""
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local
    )


class _EpochSampler:
    """Shared sampling core: seeded per-epoch permutation with wrap-fill.

    Both loaders derive their epoch order from here so the transport choice
    (host-sharded upload vs device-resident gather) can never change WHICH
    tiles a run trains on.
    """

    ds: "TileDataset"
    super_batch: int
    shuffle: bool
    seed: int
    tail: str = "wrap"

    def __len__(self) -> int:
        if self.tail == "wrap":
            return -(-len(self.ds) // self.super_batch)
        return len(self.ds) // self.super_batch

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        self.ds.set_epoch(epoch)

    def _epoch_indices(self) -> np.ndarray:
        idx = np.arange(len(self.ds))
        if self.shuffle:
            # Same permutation on every process (shared seed), like
            # DistributedSampler.set_epoch; the per-process slice differs.
            np.random.default_rng(self.seed + self._epoch).shuffle(idx)
        if self.tail == "wrap":
            # Pad to a whole number of super-batches by wrapping, so every
            # tile appears at least once and shapes stay static for XLA.
            idx = np.resize(idx, len(self) * self.super_batch)
        return idx


class ShardedLoader(_EpochSampler):
    """Iterates (images, labels) super-batches, sharded over the mesh.

    One "item" feeds one optimizer step: ``sync_period`` micro-batches of
    global size ``global_micro_batch``.  Every process computes the same
    epoch permutation (seeded), takes its contiguous per-process slice, and
    uploads only that slice.

    ``tail='wrap'`` (default) pads the epoch to a whole number of
    super-batches by wrapping the permutation, so every tile is seen at
    least once per epoch regardless of batch arithmetic — the reference
    consumes all 127 tiles each epoch at batch 1 (кластер.py:720-750), and
    large-batch configs must not refuse reference-scale datasets.
    ``tail='drop'`` keeps the old drop-remainder semantics (and rejects
    datasets smaller than one super-batch).
    """

    def __init__(
        self,
        dataset: TileDataset,
        mesh: Mesh,
        global_micro_batch: int,
        sync_period: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        data_axis: str = "data",
        space_axis: Optional[str] = None,
        prefetch: int = 2,
        tail: str = "wrap",
        compact: bool = False,
        workers: int = 1,
    ):
        self.ds = dataset
        self.mesh = mesh
        self.global_micro_batch = global_micro_batch
        self.sync_period = sync_period
        self.shuffle = shuffle
        self.seed = seed
        self.data_axis = data_axis
        self.space_axis = space_axis
        self.prefetch = prefetch
        # compact=True ships bf16 images + int8 labels over the host link —
        # 44% of the fp32 bytes.  For this zoo's bf16-compute models the
        # post-cast values are identical (the first conv casts inputs to
        # bf16 regardless; the loss clips/casts labels itself): step-level
        # bit-identity is test-pinned, and end-to-end fit() agrees to one
        # fp32 ulp (XLA compiles a separate program per input dtype and may
        # fuse a reduction differently).  Requires labels in [-1, 127];
        # asserted per batch in the producer thread.
        self.compact = compact
        # Host-side parallelism for gather+cast+upload (SURVEY §7 hard
        # part (c): ≥400 tiles/s/chip needs prefetch + host parallelism).
        # 1 keeps the single-background-thread behavior; batches stay
        # byte-identical and ordered for any value (tests/test_data.py).
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.workers = workers
        self._epoch = 0

        nproc = jax.process_count()
        if global_micro_batch % nproc:
            raise ValueError(
                f"global_micro_batch={global_micro_batch} must divide evenly "
                f"across {nproc} processes"
            )
        data_size = mesh.shape.get(data_axis, 1)
        if global_micro_batch % data_size:
            raise ValueError(
                f"global_micro_batch={global_micro_batch} must be divisible by "
                f"the '{data_axis}' mesh axis size {data_size}"
            )
        self.local_micro_batch = global_micro_batch // nproc
        self.super_batch = global_micro_batch * sync_period
        if tail not in ("wrap", "drop"):
            raise ValueError(f"tail must be 'wrap' or 'drop', got {tail!r}")
        self.tail = tail
        if len(dataset) == 0:
            raise ValueError("dataset is empty")
        if tail == "drop" and len(dataset) < self.super_batch:
            raise ValueError(
                f"dataset of {len(dataset)} tiles smaller than one super-batch "
                f"({self.super_batch} = {global_micro_batch}×{sync_period}) "
                f"with tail='drop'; use tail='wrap', reduce batch/sync_period, "
                f"or add data"
            )
        self.image_spec = P(None, data_axis, space_axis)  # [A, B, H, W, C]
        self.label_spec = P(None, data_axis, space_axis)  # [A, B, H, W]

    def _super_batch_index_chunks(self) -> Iterator[np.ndarray]:
        """This process's flat tile indices, one array per super-batch."""
        idx = self._epoch_indices()
        pid = jax.process_index()
        A, Bg, Bl = self.sync_period, self.global_micro_batch, self.local_micro_batch
        for start in range(0, len(idx) - self.super_batch + 1, self.super_batch):
            chunk = idx[start : start + self.super_batch].reshape(A, Bg)
            yield chunk[:, pid * Bl : (pid + 1) * Bl].reshape(-1)

    def _produce_host(self, flat: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """flat indices → host-side [A, B_local, ...] arrays (gather, the
        optional compact cast, reshape) — everything except the upload."""
        A, Bl = self.sync_period, self.local_micro_batch
        imgs, labs = self.ds.gather(flat)
        if self.compact:
            # Cast on the host (worker thread — overlaps consumer compute)
            # so the upload moves 44% of the fp32 bytes.
            imgs, labs = _compact_cast(imgs, labs)
        return (
            imgs.reshape(A, Bl, *imgs.shape[1:]),
            labs.reshape(A, Bl, *labs.shape[1:]),
        )

    def _local_batches(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for flat in self._super_batch_index_chunks():
            yield self._produce_host(flat)

    def _upload(self, item: Tuple[np.ndarray, np.ndarray]):
        imgs, labs = item
        return (
            make_global_array(imgs, self.mesh, self.image_spec),
            make_global_array(labs, self.mesh, self.label_spec),
        )

    def _produce(self, flat: np.ndarray):
        return self._upload(self._produce_host(flat))

    def __iter__(self) -> Iterator[Tuple[jax.Array, jax.Array]]:
        """Yield device-resident super-batches in epoch order, with the
        gather/cast/upload of up to ``prefetch`` future batches running on
        ``workers`` threads while the consumer computes (the reference's
        loop blocks the GPU on every host copy, кластер.py:754; numpy's
        large copies/casts and the device upload release the GIL, so
        workers > 1 scales with cores on a real pod host).

        Ordering and content are identical for any worker count: batches
        are yielded strictly in submission order, and each batch is a pure
        function of its index chunk.  An exception in any worker surfaces
        at that batch's position; an early consumer ``break`` waits only
        for the ≤ max(prefetch, workers)+1 already-submitted short tasks —
        the in-flight depth covers the worker count (see below), not just
        ``prefetch``, so ``workers > prefetch`` raises the number of
        uploaded super-batches resident in HBM accordingly
        (DataConfig.loader_workers documents the budget implication).
        """
        if self.prefetch <= 0:
            for flat in self._super_batch_index_chunks():
                yield self._produce(flat)
            return
        # In-flight depth must cover the worker count or extra workers sit
        # idle forever (one submit per consumed batch): workers=N implies
        # at least N batches in flight, at the corresponding memory cost.
        depth = max(self.prefetch, self.workers)
        with ThreadPoolExecutor(max_workers=self.workers) as ex:
            pending: deque = deque()
            for flat in self._super_batch_index_chunks():
                pending.append(ex.submit(self._produce, flat))
                while len(pending) > depth:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()


class DeviceCachedLoader(_EpochSampler):
    """Whole-dataset-on-HBM loader: upload once, gather batches on device.

    For corpora that fit HBM (ISPRS scale: 127 × 512²×3 fp32 ≈ 400 MB) the
    per-epoch host→device re-upload is the bottleneck — on a tunneled or
    DCN-attached host it can be 30-60× the step's compute time.  This
    loader uploads the tile arrays ONCE (replicated), then every
    super-batch is a compiled on-device ``take`` resharded onto the data
    axis; epochs cost zero host-link bytes.

    Same iterator contract as :class:`ShardedLoader` (wrap-fill epochs,
    seeded shared permutation, ``set_epoch``).  Single-process only: with
    multiple hosts each process holds only its slice of the data, so
    replicated upload would need a cross-host gather — use ShardedLoader
    there (its prefetch overlaps the uploads instead).
    """

    def __init__(
        self,
        dataset: TileDataset,
        mesh: Mesh,
        global_micro_batch: int,
        sync_period: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        data_axis: str = "data",
        space_axis: Optional[str] = None,
        compact: bool = False,
    ):
        if jax.process_count() != 1:
            raise ValueError(
                "DeviceCachedLoader is single-process (replicated upload); "
                "use ShardedLoader for multi-host runs"
            )
        if not isinstance(dataset, TileDataset):
            raise ValueError(
                "DeviceCachedLoader needs a fixed-tile TileDataset (crop "
                "datasets materialize tiles on the host per epoch)"
            )
        if len(dataset) == 0:
            raise ValueError("dataset is empty")
        data_size = mesh.shape.get(data_axis, 1)
        if global_micro_batch % data_size:
            raise ValueError(
                f"global_micro_batch={global_micro_batch} must be divisible "
                f"by the '{data_axis}' mesh axis size {data_size}"
            )
        self.ds = dataset
        self.mesh = mesh
        self.global_micro_batch = global_micro_batch
        self.sync_period = sync_period
        self.shuffle = shuffle
        self.seed = seed
        self.tail = "wrap"
        self.super_batch = global_micro_batch * sync_period
        # compact=True keeps the RESIDENT cache bf16/int8 — 44% of the fp32
        # HBM for the cached corpus (same numerics argument as the
        # ShardedLoader's compact wire: the zoo's first conv casts inputs
        # to bf16 regardless, and the loss clips/casts labels; round-4's
        # pod emulation measured the device-resident form bit-identical).
        self.compact = compact
        img_host, lab_host = (
            _compact_cast(dataset.images, dataset.labels) if compact
            else (dataset.images, dataset.labels)
        )
        self._epoch = 0
        repl = NamedSharding(mesh, P())
        self._images = jax.device_put(img_host, repl)
        self._labels = jax.device_put(lab_host, repl)
        batch_sh = NamedSharding(mesh, P(None, data_axis, space_axis))
        A, B = sync_period, global_micro_batch
        h, w, c = dataset.image_shape

        @jax.jit
        def gather(images, labels, idx):
            bx = jnp.take(images, idx, axis=0).reshape(A, B, h, w, c)
            by = jnp.take(labels, idx, axis=0).reshape(A, B, h, w)
            return (
                jax.lax.with_sharding_constraint(bx, batch_sh),
                jax.lax.with_sharding_constraint(by, batch_sh),
            )

        self._gather = gather

    def __iter__(self):
        idx = self._epoch_indices()
        for start in range(0, len(idx), self.super_batch):
            chunk = jnp.asarray(idx[start : start + self.super_batch])
            yield self._gather(self._images, self._labels, chunk)


def eval_batches(
    dataset: TileDataset,
    mesh: Mesh,
    global_batch: int,
    data_axis: str = "data",
    space_axis: Optional[str] = None,
) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Fixed-order eval iterator; pads the tail batch by repeating the last
    tile (static shapes for one compiled eval step) with labels set to -1,
    which the confusion matrix masks out (ops/metrics.py), so padding never
    pollutes mIoU."""
    nproc, pid = jax.process_count(), jax.process_index()
    if global_batch % nproc:
        raise ValueError(
            f"global_batch={global_batch} must be divisible by the process "
            f"count {nproc}"
        )
    data_size = mesh.shape.get(data_axis, 1)
    if global_batch % data_size:
        raise ValueError(
            f"global_batch={global_batch} must be divisible by the "
            f"'{data_axis}' mesh axis size {data_size}"
        )
    bl = global_batch // nproc
    spec_x = P(data_axis, space_axis)
    spec_y = P(data_axis, space_axis)
    n = len(dataset)
    for start in range(0, n, global_batch):
        idx = np.arange(start, min(start + global_batch, n))
        valid = len(idx)
        if valid < global_batch:
            idx = np.concatenate([idx, np.full(global_batch - valid, idx[-1])])
        local = idx[pid * bl : (pid + 1) * bl]
        images, labels = dataset.gather(local)
        # Mark padded samples invalid: global positions >= valid.
        global_pos = np.arange(pid * bl, (pid + 1) * bl)
        labels[global_pos >= valid] = -1
        yield (
            make_global_array(images, mesh, spec_x),
            make_global_array(labels, mesh, spec_y),
        )
