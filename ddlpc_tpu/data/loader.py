"""Host-sharded batching: numpy tiles → globally-sharded jax.Arrays.

This fixes the reference's central data defect: every replica there trains on
the *same* 127 tiles in the *same* order (its shuffle is computed then never
applied, кластер.py:722-723,750; SURVEY §3.1), so k replicas do k× redundant
work.  Here one global permutation (same seed on every process) is sliced
per-process, each host materializes only its slice, and
``jax.make_array_from_process_local_data`` assembles the global sharded batch
the compiled step consumes — the standard multi-host JAX input path, replacing
nothing-in-the-reference (it has no sampler at all).

Batch layout for the train step (parallel/train_step.py):
  images [A, B, H, W, C], labels [A, B, H, W]
A = sync_period micro-batches per optimizer step (reference
``frequency_sending_gradients``, кластер.py:685), B = global micro-batch
sharded over the mesh ``data`` axis (and H over ``space`` when used).
"""

from __future__ import annotations

import threading
import warnings
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from contextlib import nullcontext
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlpc_tpu.analysis import lockcheck
from ddlpc_tpu.data.datasets import TileDataset, gather_into as _gather_into
from ddlpc_tpu.utils import native as _native


def _compact_cast(
    imgs: np.ndarray, labs: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """fp32/int32 → bf16/int8 (44% of the bytes), shared by BOTH transports
    so the wire form and the resident-cache form can never drift.  Labels
    must fit int8 with the −1 void sentinel (contract owned by
    utils/native.py so the numpy and kernel paths cannot diverge)."""
    _native.check_label_range(labs.min(), labs.max())
    return imgs.astype(ml_dtypes.bfloat16), labs.astype(np.int8)


def make_global_array(
    local: np.ndarray, mesh: Mesh, spec: P
) -> jax.Array:
    """Assemble a global sharded array from this process's local shard."""
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local
    )


_warned_native_fallback = False


def _warn_native_fallback() -> None:
    """One warning per process when native_gather is requested but the
    kernel is unavailable — the same silent-degradation discipline wire.py
    avoids: the run keeps working on the byte-identical numpy path, but the
    operator can see WHY the host input rate is 1-core-bound."""
    global _warned_native_fallback
    if not _warned_native_fallback:
        _warned_native_fallback = True
        warnings.warn(
            "native batch kernel unavailable (csrc/libdwbatch.so missing and "
            "not buildable — is g++ installed?); ShardedLoader falls back to "
            "the single-threaded numpy gather path (byte-identical, slower). "
            "Run `make -C csrc batch` to build it, or set "
            "DataConfig.native_gather=false to silence this.",
            RuntimeWarning,
            stacklevel=3,
        )


def _aliases_host_storage(arrays, spans) -> bool:
    """Whether any device shard of ``arrays`` zero-copy aliases one of the
    host buffer ``spans`` ([start, end) address ranges).

    Some backends' host→device transfer (notably CPU clients) may alias a
    suitably-aligned numpy buffer instead of copying, and whether a given
    buffer qualifies depends on its alignment and transfer path — so this
    is checked per upload against the ACTUAL uploaded arrays, not probed
    once with a stand-in.  Decides the ring's recycling policy: real
    copies (TPU HBM) → the slot is reusable once the transfer completes;
    aliased → the slot's storage is handed to the array and the ring
    refills with a fresh allocation (the pre-ring behavior — correctness
    first).  Unverifiable shards count as aliased."""
    for ga in arrays:
        for shard in ga.addressable_shards:
            try:
                p = shard.data.unsafe_buffer_pointer()
            except Exception:
                return True
            if any(lo <= p < hi for lo, hi in spans):
                return True
    return False


class _Slot:
    """One ring entry: the final [A, B_local, ...] destination pair plus
    (only when the compact cast cannot fuse with the gather) fp32/int32
    scratch for the gather stage."""

    __slots__ = ("imgs", "labs", "scratch_imgs", "scratch_labs")

    def __init__(self, imgs, labs, scratch_imgs=None, scratch_labs=None):
        self.imgs = imgs
        self.labs = labs
        self.scratch_imgs = scratch_imgs
        self.scratch_labs = scratch_labs


@lockcheck.guarded
class _HostRing:
    """Fixed pool of preallocated super-batch destination buffers.

    ``acquire`` blocks until a slot is free; ``release`` returns it —
    or, with ``retire=True``, hands the slot's DESTINATION storage to
    whoever aliased it (an uploaded device array) and refills the pool
    with a fresh allocation, so the pool size is invariant either way.
    The replacement is allocated outside the lock (it can be hundreds of
    MB — other producers must not serialize behind it) and keeps the old
    slot's scratch buffers, which are never uploaded and so never
    aliased."""

    def __init__(self, nslots: int, alloc):
        # alloc(reuse_scratch_from=None) builds a slot, optionally
        # adopting an existing slot's scratch pair.
        self._alloc = alloc
        self._cv = lockcheck.condition("_HostRing._cv")
        self._slots = [alloc() for _ in range(nslots)]  # guarded-by: _cv

    def acquire(self) -> _Slot:
        with self._cv:
            while not self._slots:
                self._cv.wait()
            return self._slots.pop()

    def release(self, slot: _Slot, retire: bool = False) -> None:
        if retire:
            slot = self._alloc(reuse_scratch_from=slot)
        with self._cv:
            self._slots.append(slot)
            self._cv.notify()


class _EpochSampler:
    """Shared sampling core: seeded per-epoch permutation with wrap-fill.

    Both loaders derive their epoch order from here so the transport choice
    (host-sharded upload vs device-resident gather) can never change WHICH
    tiles a run trains on.
    """

    ds: "TileDataset"
    super_batch: int
    shuffle: bool
    seed: int
    tail: str = "wrap"

    def __len__(self) -> int:
        if self.tail == "wrap":
            return -(-len(self.ds) // self.super_batch)
        return len(self.ds) // self.super_batch

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        self.ds.set_epoch(epoch)

    def _epoch_indices(self) -> np.ndarray:
        idx = np.arange(len(self.ds))
        if self.shuffle:
            # Same permutation on every process (shared seed), like
            # DistributedSampler.set_epoch; the per-process slice differs.
            np.random.default_rng(self.seed + self._epoch).shuffle(idx)
        if self.tail == "wrap":
            # Pad to a whole number of super-batches by wrapping, so every
            # tile appears at least once and shapes stay static for XLA.
            idx = np.resize(idx, len(self) * self.super_batch)
        return idx


class ShardedLoader(_EpochSampler):
    """Iterates (images, labels) super-batches, sharded over the mesh.

    One "item" feeds one optimizer step: ``sync_period`` micro-batches of
    global size ``global_micro_batch``.  Every process computes the same
    epoch permutation (seeded), takes its contiguous per-process slice, and
    uploads only that slice.

    Host assembly runs through a ring of ``max(prefetch, workers) + 1``
    preallocated destination buffers and, by default, the native fused
    gather–cast–pack kernel (csrc/batch.cc, ``native_gather``): one
    multithreaded memory pass per super-batch instead of numpy's
    single-threaded gather copy + astype copy + per-batch allocation.
    Byte-identical to the numpy fallback (tests/test_native_batch.py);
    per-stage host timings flow into ``timer`` when one is supplied
    (docs/PERF.md "Host-upload path isolated").

    ``tail='wrap'`` (default) pads the epoch to a whole number of
    super-batches by wrapping the permutation, so every tile is seen at
    least once per epoch regardless of batch arithmetic — the reference
    consumes all 127 tiles each epoch at batch 1 (кластер.py:720-750), and
    large-batch configs must not refuse reference-scale datasets.
    ``tail='drop'`` keeps the old drop-remainder semantics (and rejects
    datasets smaller than one super-batch).
    """

    def __init__(
        self,
        dataset: TileDataset,
        mesh: Mesh,
        global_micro_batch: int,
        sync_period: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        data_axis: str = "data",
        space_axis: Optional[str] = None,
        prefetch: int = 2,
        tail: str = "wrap",
        compact: bool = False,
        workers: int = 1,
        native_gather: bool = True,
        timer=None,
    ):
        self.ds = dataset
        self.mesh = mesh
        self.global_micro_batch = global_micro_batch
        self.sync_period = sync_period
        self.shuffle = shuffle
        self.seed = seed
        self.data_axis = data_axis
        self.space_axis = space_axis
        self.prefetch = prefetch
        # compact=True ships bf16 images + int8 labels over the host link —
        # 44% of the fp32 bytes.  For this zoo's bf16-compute models the
        # post-cast values are identical (the first conv casts inputs to
        # bf16 regardless; the loss clips/casts labels itself): step-level
        # bit-identity is test-pinned, and end-to-end fit() agrees to one
        # fp32 ulp (XLA compiles a separate program per input dtype and may
        # fuse a reduction differently).  Requires labels in [-1, 127];
        # asserted per batch in the producer thread.
        self.compact = compact
        # Host-side parallelism for gather+cast+upload (SURVEY §7 hard
        # part (c): ≥400 tiles/s/chip needs prefetch + host parallelism).
        # 1 keeps the single-background-thread behavior; batches stay
        # byte-identical and ordered for any value (tests/test_data.py).
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.workers = workers
        # Native fused gather–cast–pack (csrc/batch.cc): one multithreaded
        # memory pass instead of numpy's separate gather copy + astype copy,
        # writing straight into the ring's packed destination buffer.  When
        # the kernel is unavailable (no g++, no prebuilt .so) the loader
        # logs once and runs the byte-identical numpy path — same fallback
        # discipline as the wire codec (utils/wire.py).
        self.native_gather = native_gather
        self._native = _native.load_batch() if native_gather else None
        if native_gather and self._native is None:
            _warn_native_fallback()
        # Optional StageTimer: per-stage host timings (loader_gather /
        # loader_cast / loader_upload) surface in the trainer's metrics
        # JSONL next to t_data/t_step.  Must be thread-safe (StageTimer
        # is) — stages run on producer threads.
        self.timer = timer
        self._ring: Optional[_HostRing] = None
        self._iota_cache: Optional[np.ndarray] = None
        self._epoch = 0

        nproc = jax.process_count()
        if global_micro_batch % nproc:
            raise ValueError(
                f"global_micro_batch={global_micro_batch} must divide evenly "
                f"across {nproc} processes"
            )
        data_size = mesh.shape.get(data_axis, 1)
        if global_micro_batch % data_size:
            raise ValueError(
                f"global_micro_batch={global_micro_batch} must be divisible by "
                f"the '{data_axis}' mesh axis size {data_size}"
            )
        self.local_micro_batch = global_micro_batch // nproc
        self.super_batch = global_micro_batch * sync_period
        if tail not in ("wrap", "drop"):
            raise ValueError(f"tail must be 'wrap' or 'drop', got {tail!r}")
        self.tail = tail
        if len(dataset) == 0:
            raise ValueError("dataset is empty")
        if tail == "drop" and len(dataset) < self.super_batch:
            raise ValueError(
                f"dataset of {len(dataset)} tiles smaller than one super-batch "
                f"({self.super_batch} = {global_micro_batch}×{sync_period}) "
                f"with tail='drop'; use tail='wrap', reduce batch/sync_period, "
                f"or add data"
            )
        self.image_spec = P(None, data_axis, space_axis)  # [A, B, H, W, C]
        self.label_spec = P(None, data_axis, space_axis)  # [A, B, H, W]

    def _super_batch_index_chunks(self) -> Iterator[np.ndarray]:
        """This process's flat tile indices, one array per super-batch."""
        idx = self._epoch_indices()
        pid = jax.process_index()
        A, Bg, Bl = self.sync_period, self.global_micro_batch, self.local_micro_batch
        for start in range(0, len(idx) - self.super_batch + 1, self.super_batch):
            chunk = idx[start : start + self.super_batch].reshape(A, Bg)
            yield chunk[:, pid * Bl : (pid + 1) * Bl].reshape(-1)

    # ---- host-side assembly: buffer ring + fused native kernel ---------

    def _stage(self, name: str):
        return (
            self.timer.stage(f"loader_{name}")
            if self.timer is not None
            else nullcontext()
        )

    def _native_source(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """The dataset's resident (fp32, int32) arrays when the fused
        kernel can gather from them directly; None for lazy/crop/augment
        sources (those materialize per gather — the kernel still fuses
        their compact cast+pack through the scratch stage)."""
        imgs = getattr(self.ds, "images", None)
        labs = getattr(self.ds, "labels", None)
        if (
            isinstance(imgs, np.ndarray)
            and isinstance(labs, np.ndarray)
            and imgs.dtype == np.float32
            and labs.dtype == np.int32
            and imgs.flags.c_contiguous
            and labs.flags.c_contiguous
        ):
            return imgs, labs
        return None

    def _get_ring(self) -> _HostRing:
        """The destination-buffer ring, sized to the in-flight depth + the
        one batch the consumer holds, so steady-state epochs allocate
        nothing on the host (buffers are reused, not reallocated)."""
        if self._ring is None:
            A, Bl = self.sync_period, self.local_micro_batch
            h, w, c = self.ds.image_shape
            img_dt = ml_dtypes.bfloat16 if self.compact else np.float32
            lab_dt = np.int8 if self.compact else np.int32

            # Scratch (fp32/int32 staging for a compact cast that cannot
            # fuse into the gather) is allocated lazily per slot on first
            # need (_ensure_scratch) and retained, rather than decided
            # here: whether it is needed depends on the dataset, which a
            # caller may swap after the ring exists (the instrumentation-
            # wrapper pattern in scripts/multiproc_trainer.py).
            def alloc(reuse_scratch_from: Optional[_Slot] = None) -> _Slot:
                old = reuse_scratch_from
                return _Slot(
                    np.empty((A, Bl, h, w, c), img_dt),
                    np.empty((A, Bl, h, w), lab_dt),
                    old.scratch_imgs if old is not None else None,
                    old.scratch_labs if old is not None else None,
                )

            self._ring = _HostRing(max(self.prefetch, self.workers) + 1, alloc)
        return self._ring

    def _iota(self, n: int) -> np.ndarray:
        if self._iota_cache is None or len(self._iota_cache) != n:
            self._iota_cache = np.arange(n, dtype=np.int64)
        return self._iota_cache

    def _ensure_scratch(self, slot: _Slot) -> None:
        if slot.scratch_imgs is None:
            h, w, c = self.ds.image_shape
            T = self.sync_period * self.local_micro_batch
            slot.scratch_imgs = np.empty((T, h, w, c), np.float32)
            slot.scratch_labs = np.empty((T, h, w), np.int32)

    def _assemble(
        self, flat: np.ndarray, slot: _Slot
    ) -> Tuple[np.ndarray, np.ndarray]:
        """flat indices → the slot's packed [A, B_local, ...] pair.

        Three routes, all byte-identical (test-pinned):
        - resident source + native kernel: ONE fused gather(+cast)+pack
          memory pass, multithreaded (the tentpole fast path);
        - compact without that fusion: gather fp32/int32 into the slot's
          scratch, then one cast+pack pass (native when available, else
          numpy copyto after the [-1, 127] label check);
        - plain fp32: gather directly into the destination buffer.
        There is no separate pack pass anywhere: the ring slot IS the
        [A, B_local, H, W, C] layout, so packing is where bytes land.
        """
        flat = np.ascontiguousarray(flat, np.int64)
        imgs, labs = slot.imgs, slot.labs
        src = self._native_source() if self._native is not None else None
        if src is not None:
            with self._stage("gather"):
                self._native.gather_pack(
                    src[0], src[1], flat, imgs, labs, self.compact
                )
        elif self.compact:
            self._ensure_scratch(slot)
            with self._stage("gather"):
                _gather_into(self.ds, flat, slot.scratch_imgs, slot.scratch_labs)
            with self._stage("cast"):
                if self._native is not None:
                    self._native.gather_pack(
                        slot.scratch_imgs,
                        slot.scratch_labs,
                        self._iota(len(flat)),
                        imgs,
                        labs,
                        True,
                    )
                else:
                    _native.check_label_range(
                        slot.scratch_labs.min(), slot.scratch_labs.max()
                    )
                    np.copyto(
                        imgs.reshape(slot.scratch_imgs.shape),
                        slot.scratch_imgs,
                        casting="unsafe",
                    )
                    np.copyto(
                        labs.reshape(slot.scratch_labs.shape),
                        slot.scratch_labs,
                        casting="unsafe",
                    )
        else:
            with self._stage("gather"):
                _gather_into(self.ds, flat, imgs, labs)
        return imgs, labs

    def _local_batches(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield host-side [A, B_local, ...] pairs, one per super-batch.

        The yielded arrays are the loader's ring buffers and stay valid
        only until the next iteration step (the slot is recycled when the
        generator resumes) — consumers that retain a batch must copy.
        ``__iter__`` has no such caveat: it yields device arrays whose
        backing transfer completed (or owns the storage outright)."""
        for flat in self._super_batch_index_chunks():
            slot = self._get_ring().acquire()
            try:
                yield self._assemble(flat, slot)
            finally:
                self._get_ring().release(slot)

    def _upload(self, item: Tuple[np.ndarray, np.ndarray]):
        imgs, labs = item
        return (
            make_global_array(imgs, self.mesh, self.image_spec),
            make_global_array(labs, self.mesh, self.label_spec),
        )

    def _produce(self, flat: np.ndarray):
        ring = self._get_ring()
        slot = ring.acquire()
        retire = False
        try:
            host = self._assemble(flat, slot)
            with self._stage("upload"):
                out = self._upload(host)
                spans = [
                    (a.ctypes.data, a.ctypes.data + a.nbytes)
                    for a in (slot.imgs, slot.labs)
                ]
                if _aliases_host_storage(out, spans):
                    # The "device" arrays share the slot's storage (CPU
                    # zero-copy): hand it over, refill with a fresh slot
                    # — the pre-ring allocation rate, never a stale batch.
                    retire = True
                else:
                    # Real copies (TPU HBM): once the transfer lands the
                    # slot is reusable — zero host allocation per batch.
                    for a in out:
                        a.block_until_ready()
            return out
        finally:
            ring.release(slot, retire=retire)

    def __iter__(self) -> Iterator[Tuple[jax.Array, jax.Array]]:
        """Yield device-resident super-batches in epoch order, with the
        gather/cast/upload of up to ``prefetch`` future batches running on
        ``workers`` threads while the consumer computes (the reference's
        loop blocks the GPU on every host copy, кластер.py:754; numpy's
        large copies/casts and the device upload release the GIL, so
        workers > 1 scales with cores on a real pod host).

        Ordering and content are identical for any worker count: batches
        are yielded strictly in submission order, and each batch is a pure
        function of its index chunk.  An exception in any worker surfaces
        at that batch's position; an early consumer ``break`` waits only
        for the ≤ max(prefetch, workers)+1 already-submitted short tasks —
        the in-flight depth covers the worker count (see below), not just
        ``prefetch``, so ``workers > prefetch`` raises the number of
        uploaded super-batches resident in HBM accordingly
        (DataConfig.loader_workers documents the budget implication).
        """
        if self.prefetch <= 0:
            for flat in self._super_batch_index_chunks():
                yield self._produce(flat)
            return
        # Materialize the ring on the consumer thread before the pool
        # starts: it is lazily built and concurrent first-touch from
        # workers would race the construction.
        self._get_ring()
        # In-flight depth must cover the worker count or extra workers sit
        # idle forever (one submit per consumed batch): workers=N implies
        # at least N batches in flight, at the corresponding memory cost.
        depth = max(self.prefetch, self.workers)
        with ThreadPoolExecutor(max_workers=self.workers) as ex:
            pending: deque = deque()
            for flat in self._super_batch_index_chunks():
                pending.append(ex.submit(self._produce, flat))
                while len(pending) > depth:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()


class DeviceCachedLoader(_EpochSampler):
    """Whole-dataset-on-HBM loader: upload once, gather batches on device.

    For corpora that fit HBM (ISPRS scale: 127 × 512²×3 fp32 ≈ 400 MB) the
    per-epoch host→device re-upload is the bottleneck — on a tunneled or
    DCN-attached host it can be 30-60× the step's compute time.  This
    loader uploads the tile arrays ONCE (replicated), then every
    super-batch is a compiled on-device ``take`` resharded onto the data
    axis; epochs cost zero host-link bytes.

    Same iterator contract as :class:`ShardedLoader` (wrap-fill epochs,
    seeded shared permutation, ``set_epoch``).  Single-process only: with
    multiple hosts each process holds only its slice of the data, so
    replicated upload would need a cross-host gather — use ShardedLoader
    there (its prefetch overlaps the uploads instead).
    """

    def __init__(
        self,
        dataset: TileDataset,
        mesh: Mesh,
        global_micro_batch: int,
        sync_period: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        data_axis: str = "data",
        space_axis: Optional[str] = None,
        compact: bool = False,
    ):
        if jax.process_count() != 1:
            raise ValueError(
                "DeviceCachedLoader is single-process (replicated upload); "
                "use ShardedLoader for multi-host runs"
            )
        if not isinstance(dataset, TileDataset):
            raise ValueError(
                "DeviceCachedLoader needs a fixed-tile TileDataset (crop "
                "datasets materialize tiles on the host per epoch)"
            )
        if len(dataset) == 0:
            raise ValueError("dataset is empty")
        data_size = mesh.shape.get(data_axis, 1)
        if global_micro_batch % data_size:
            raise ValueError(
                f"global_micro_batch={global_micro_batch} must be divisible "
                f"by the '{data_axis}' mesh axis size {data_size}"
            )
        self.ds = dataset
        self.mesh = mesh
        self.global_micro_batch = global_micro_batch
        self.sync_period = sync_period
        self.shuffle = shuffle
        self.seed = seed
        self.tail = "wrap"
        self.super_batch = global_micro_batch * sync_period
        # compact=True keeps the RESIDENT cache bf16/int8 — 44% of the fp32
        # HBM for the cached corpus (same numerics argument as the
        # ShardedLoader's compact wire: the zoo's first conv casts inputs
        # to bf16 regardless, and the loss clips/casts labels; round-4's
        # pod emulation measured the device-resident form bit-identical).
        self.compact = compact
        img_host, lab_host = (
            _compact_cast(dataset.images, dataset.labels) if compact
            else (dataset.images, dataset.labels)
        )
        self._epoch = 0
        repl = NamedSharding(mesh, P())
        self._images = jax.device_put(img_host, repl)
        self._labels = jax.device_put(lab_host, repl)
        batch_sh = NamedSharding(mesh, P(None, data_axis, space_axis))
        A, B = sync_period, global_micro_batch
        h, w, c = dataset.image_shape

        @jax.jit
        def gather(images, labels, idx):
            bx = jnp.take(images, idx, axis=0).reshape(A, B, h, w, c)
            by = jnp.take(labels, idx, axis=0).reshape(A, B, h, w)
            return (
                jax.lax.with_sharding_constraint(bx, batch_sh),
                jax.lax.with_sharding_constraint(by, batch_sh),
            )

        self._gather = gather

    def __iter__(self):
        idx = self._epoch_indices()
        for start in range(0, len(idx), self.super_batch):
            chunk = jnp.asarray(idx[start : start + self.super_batch])
            yield self._gather(self._images, self._labels, chunk)


def eval_batches(
    dataset: TileDataset,
    mesh: Mesh,
    global_batch: int,
    data_axis: str = "data",
    space_axis: Optional[str] = None,
) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Fixed-order eval iterator; pads the tail batch by repeating the last
    tile (static shapes for one compiled eval step) with labels set to -1,
    which the confusion matrix masks out (ops/metrics.py), so padding never
    pollutes mIoU."""
    nproc, pid = jax.process_count(), jax.process_index()
    if global_batch % nproc:
        raise ValueError(
            f"global_batch={global_batch} must be divisible by the process "
            f"count {nproc}"
        )
    data_size = mesh.shape.get(data_axis, 1)
    if global_batch % data_size:
        raise ValueError(
            f"global_batch={global_batch} must be divisible by the "
            f"'{data_axis}' mesh axis size {data_size}"
        )
    bl = global_batch // nproc
    spec_x = P(data_axis, space_axis)
    spec_y = P(data_axis, space_axis)
    n = len(dataset)
    for start in range(0, n, global_batch):
        idx = np.arange(start, min(start + global_batch, n))
        valid = len(idx)
        if valid < global_batch:
            idx = np.concatenate([idx, np.full(global_batch - valid, idx[-1])])
        local = idx[pid * bl : (pid + 1) * bl]
        images, labels = dataset.gather(local)
        # Mark padded samples invalid: global positions >= valid.
        global_pos = np.arange(pid * bl, (pid + 1) * bl)
        labels[global_pos >= valid] = -1
        yield (
            make_global_array(images, mesh, spec_x),
            make_global_array(labels, mesh, spec_y),
        )
