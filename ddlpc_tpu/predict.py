"""Inference CLI: ``python -m ddlpc_tpu.predict --workdir runs/x --input dir``.

The reference has no inference path at all — its closest artifact is the
in-training PNG dump of fixed 512×512 crops (кластер.py:785-790,817-823).
This restores a trained checkpoint and predicts each input image at its
NATIVE size via overlap-blended sliding windows, writing a color-mapped
class-map PNG per input.

This is now a thin client of :mod:`ddlpc_tpu.serve.engine`: the tiler and
restore logic live there (one tested path shared with the serving engine);
``sliding_window_logits`` and ``load_run`` stay re-exported here for
existing callers.  Restore goes through the format-dispatching checkpoint
reader (train/checkpoint.py): both the chunked ``.dwc`` format and legacy
single-blob ``.msgpack.z`` checkpoints load here unchanged
(docs/CHECKPOINTS.md).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from ddlpc_tpu.serve.engine import (  # noqa: F401  (public re-exports)
    InferenceEngine,
    _blend_window,
    sliding_window_logits,
)


def load_run(workdir: str):
    """(cfg, state, logits_fn, channels) restored from a training run.

    Back-compat shim over ``InferenceEngine.from_workdir`` — new code should
    use the engine directly (it adds the compiled-shape cache + hot reload).
    """
    from ddlpc_tpu.parallel.train_step import make_logits_fn

    eng = InferenceEngine.from_workdir(workdir)
    return eng.cfg, eng.state, make_logits_fn(eng.model), eng.channels


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m ddlpc_tpu.predict")
    p.add_argument("--workdir", required=True, help="training run directory")
    p.add_argument("--input", required=True, help="directory of images")
    p.add_argument("--output", help="output directory (default <workdir>/predictions)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument(
        "--overlap",
        type=float,
        default=0.25,
        help="sliding-window overlap fraction (0 = edge-to-edge tiling)",
    )
    args = p.parse_args(argv)

    from PIL import Image

    from ddlpc_tpu.train.observability import class_palette

    engine = InferenceEngine.from_workdir(args.workdir, max_bucket=args.batch)
    cfg = engine.cfg

    out_dir = args.output or os.path.join(args.workdir, "predictions")
    os.makedirs(out_dir, exist_ok=True)
    pal = class_palette(cfg.model.num_classes)

    names = sorted(
        n
        for n in os.listdir(args.input)
        if not n.endswith(".npy") and os.path.isfile(os.path.join(args.input, n))
    )
    if not names:
        print(f"no images found in {args.input}", file=sys.stderr)
        return 1
    from ddlpc_tpu.data.datasets import load_image_file

    for n in names:
        # Native size (image_size=None): the sliding window handles any
        # geometry; preprocessing stays shared with the training readers.
        image = load_image_file(
            os.path.join(args.input, n), None, channels=engine.channels
        )
        pred = engine.predict_classes(
            image, overlap=args.overlap, batch=args.batch
        )
        stem = n.rsplit(".", 1)[0]
        Image.fromarray(pal[np.clip(pred, 0, cfg.model.num_classes - 1)]).save(
            os.path.join(out_dir, f"{stem}_pred.png")
        )
    print(f"wrote {len(names)} predictions to {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
