"""Inference CLI: ``python -m ddlpc_tpu.predict --workdir runs/x --input dir``.

The reference has no inference path at all — its closest artifact is the
in-training PNG dump (кластер.py:785-790).  This restores a trained
checkpoint and writes a color-mapped class-map PNG per input image.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m ddlpc_tpu.predict")
    p.add_argument("--workdir", required=True, help="training run directory")
    p.add_argument("--input", required=True, help="directory of images")
    p.add_argument("--output", help="output directory (default <workdir>/predictions)")
    p.add_argument("--batch", type=int, default=8)
    args = p.parse_args(argv)

    import jax
    from PIL import Image

    from ddlpc_tpu.config import ExperimentConfig
    from ddlpc_tpu.models import build_model
    from ddlpc_tpu.parallel.train_step import create_train_state, make_predict_fn
    from ddlpc_tpu.train import checkpoint as ckpt
    from ddlpc_tpu.train.observability import class_palette
    from ddlpc_tpu.train.optim import build_optimizer

    with open(os.path.join(args.workdir, "config.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    # Inference is single-device: no mesh axis for BN stats.
    model = build_model(cfg.model, norm_axis_name=None)
    tx = build_optimizer(cfg.train)
    h, w = cfg.data.image_size
    state = create_train_state(model, tx, jax.random.key(0), (1, h, w, 3))
    state, meta = ckpt.restore_checkpoint(
        os.path.join(args.workdir, "checkpoints"), state
    )
    print(f"restored step {meta.get('step')} (epoch {meta.get('epoch')})")
    predict = make_predict_fn(model)

    out_dir = args.output or os.path.join(args.workdir, "predictions")
    os.makedirs(out_dir, exist_ok=True)
    pal = class_palette(cfg.model.num_classes)

    from ddlpc_tpu.data.datasets import load_image_file

    names = sorted(
        n
        for n in os.listdir(args.input)
        if not n.endswith(".npy") and os.path.isfile(os.path.join(args.input, n))
    )
    if not names:
        print(f"no images found in {args.input}", file=sys.stderr)
        return 1
    for start in range(0, len(names), args.batch):
        chunk = names[start : start + args.batch]
        batch = np.stack(
            [load_image_file(os.path.join(args.input, n), (h, w)) for n in chunk]
        )
        # Pad the tail to the compiled batch size.
        valid = len(chunk)
        if valid < args.batch:
            batch = np.concatenate(
                [batch, np.repeat(batch[-1:], args.batch - valid, axis=0)]
            )
        preds = np.asarray(predict(state, batch))[:valid]
        for n, pred in zip(chunk, preds):
            stem = n.rsplit(".", 1)[0]
            Image.fromarray(pal[np.clip(pred, 0, cfg.model.num_classes - 1)]).save(
                os.path.join(out_dir, f"{stem}_pred.png")
            )
    print(f"wrote {len(names)} predictions to {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
