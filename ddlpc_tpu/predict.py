"""Inference CLI: ``python -m ddlpc_tpu.predict --workdir runs/x --input dir``.

The reference has no inference path at all — its closest artifact is the
in-training PNG dump of fixed 512×512 crops (кластер.py:785-790,817-823).
This restores a trained checkpoint and predicts each input image at its
NATIVE size via overlap-blended sliding windows, writing a color-mapped
class-map PNG per input.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Callable, Tuple

import numpy as np


def _blend_window(tile: Tuple[int, int]) -> np.ndarray:
    """[th, tw] separable triangular weights, strictly positive, peaked at
    the window center — overlapping windows cross-fade instead of seaming."""

    def ramp(n: int) -> np.ndarray:
        x = np.arange(n, dtype=np.float32)
        return np.minimum(x + 1.0, n - x) / ((n + 1) / 2)

    return np.outer(ramp(tile[0]), ramp(tile[1])).astype(np.float32)


def sliding_window_logits(
    logits_fn: Callable[..., np.ndarray],
    state,
    image: np.ndarray,
    tile: Tuple[int, int],
    overlap: float = 0.25,
    batch: int = 8,
) -> np.ndarray:
    """Full-scene logits [H, W, C] for an arbitrary-size image [H, W, c].

    Covers the scene with ``tile``-sized windows at stride
    ``tile·(1-overlap)`` (the last row/column snaps flush to the edge, so
    coverage is exact without padding unless the scene is smaller than one
    tile), runs the compiled ``logits_fn`` on fixed-size window batches, and
    blends overlaps with triangular weights.
    """
    if not 0.0 <= overlap < 1.0:
        # A negative overlap would stride past the tile, leaving wsum==0
        # gaps whose 0/0 logits silently argmax to class 0.
        raise ValueError(f"overlap must be in [0, 1), got {overlap}")
    th, tw = tile
    h, w = image.shape[:2]
    pad_h, pad_w = max(th - h, 0), max(tw - w, 0)
    if pad_h or pad_w:
        image = np.pad(image, ((0, pad_h), (0, pad_w), (0, 0)))
    H, W = image.shape[:2]

    def starts(extent: int, size: int, stride: int) -> list[int]:
        out = list(range(0, extent - size + 1, stride))
        if out[-1] != extent - size:
            out.append(extent - size)
        return out

    sh = max(int(th * (1.0 - overlap)), 1)
    sw = max(int(tw * (1.0 - overlap)), 1)
    origins = [(y, x) for y in starts(H, th, sh) for x in starts(W, tw, sw)]

    weight = _blend_window(tile)
    acc: np.ndarray | None = None
    wsum = np.zeros((H, W, 1), np.float32)
    for i in range(0, len(origins), batch):
        chunk = origins[i : i + batch]
        windows = np.stack(
            [image[y : y + th, x : x + tw] for y, x in chunk]
        )
        valid = len(chunk)
        if valid < batch:  # pad to the compiled batch size
            windows = np.concatenate(
                [windows, np.repeat(windows[-1:], batch - valid, axis=0)]
            )
        logits = np.asarray(logits_fn(state, windows), np.float32)[:valid]
        if acc is None:
            acc = np.zeros((H, W, logits.shape[-1]), np.float32)
        for (y, x), tile_logits in zip(chunk, logits):
            acc[y : y + th, x : x + tw] += tile_logits * weight[..., None]
            wsum[y : y + th, x : x + tw, 0] += weight
    assert acc is not None
    out = acc / wsum
    return out[:h, :w]


def load_run(workdir: str):
    """(cfg, state, logits_fn, channels) restored from a training run.

    Input channel count comes from the checkpoint metadata (the Trainer
    records what the dataset actually had) — NOT a hardcoded 3, which made
    non-RGB checkpoints unrestorable (ADVICE r1).
    """
    import jax

    from ddlpc_tpu.config import ExperimentConfig
    from ddlpc_tpu.models import build_model
    from ddlpc_tpu.parallel.train_step import (
        create_train_state,
        make_logits_fn,
    )
    from ddlpc_tpu.train import checkpoint as ckpt
    from ddlpc_tpu.train.optim import build_optimizer

    with open(os.path.join(workdir, "config.json")) as f:
        cfg = ExperimentConfig.from_json(f.read())
    ckpt_dir = os.path.join(workdir, "checkpoints")
    step = ckpt.latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    meta = ckpt.peek_metadata(ckpt_dir, step)
    channels = int(meta.get("input_channels", 3))
    # Inference is single-device: no mesh axis for BN stats.
    model = build_model(cfg.model, norm_axis_name=None)
    # Dummy schedule horizon: only the optimizer state STRUCTURE matters
    # for restore, and decaying schedules would refuse total_steps=None.
    tx = build_optimizer(cfg.train, total_steps=1)
    h, w = cfg.data.image_size
    state = create_train_state(
        model, tx, jax.random.key(0), (1, h, w, channels)
    )
    state, meta = ckpt.restore_checkpoint(ckpt_dir, state)
    print(f"restored step {meta.get('step')} (epoch {meta.get('epoch')})")
    return cfg, state, make_logits_fn(model), channels


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m ddlpc_tpu.predict")
    p.add_argument("--workdir", required=True, help="training run directory")
    p.add_argument("--input", required=True, help="directory of images")
    p.add_argument("--output", help="output directory (default <workdir>/predictions)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument(
        "--overlap",
        type=float,
        default=0.25,
        help="sliding-window overlap fraction (0 = edge-to-edge tiling)",
    )
    args = p.parse_args(argv)

    from PIL import Image

    from ddlpc_tpu.train.observability import class_palette

    cfg, state, logits_fn, channels = load_run(args.workdir)
    h, w = cfg.data.image_size

    out_dir = args.output or os.path.join(args.workdir, "predictions")
    os.makedirs(out_dir, exist_ok=True)
    pal = class_palette(cfg.model.num_classes)

    names = sorted(
        n
        for n in os.listdir(args.input)
        if not n.endswith(".npy") and os.path.isfile(os.path.join(args.input, n))
    )
    if not names:
        print(f"no images found in {args.input}", file=sys.stderr)
        return 1
    from ddlpc_tpu.data.datasets import load_image_file

    for n in names:
        # Native size (image_size=None): the sliding window handles any
        # geometry; preprocessing stays shared with the training readers.
        image = load_image_file(
            os.path.join(args.input, n), None, channels=channels
        )
        logits = sliding_window_logits(
            logits_fn,
            state,
            image,
            tile=(h, w),
            overlap=args.overlap,
            batch=args.batch,
        )
        pred = np.argmax(logits, axis=-1)
        stem = n.rsplit(".", 1)[0]
        Image.fromarray(pal[np.clip(pred, 0, cfg.model.num_classes - 1)]).save(
            os.path.join(out_dir, f"{stem}_pred.png")
        )
    print(f"wrote {len(names)} predictions to {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
