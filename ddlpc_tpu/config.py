"""Typed configuration system.

The reference configures everything through module-level globals and
hostname→ID tables edited by hand on every node (кластер.py:23-25, 223-252,
685-687).  Here configuration is a tree of frozen dataclasses that serializes
to/from JSON, so a run is reproducible from one artifact.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Tuple


@dataclass(frozen=True)
class ModelConfig:
    """Model-zoo selection.

    ``width_divisor`` mirrors the reference's ``NN_in_model`` global channel
    divisor (кластер.py:625,687; value 2 → half-width U-Net).
    ``up_sample_mode`` mirrors UNet(..., up_sample_mode) (кластер.py:621).
    """

    name: str = "unet"  # any name registered in models/__init__.py
    num_classes: int = 6  # Vaihingen has 6 classes (кластер.py:702)
    width_divisor: int = 1
    features: Tuple[int, ...] = (64, 128, 256, 512, 512)
    bottleneck_features: int = 512
    up_sample_mode: str = "conv_transpose"  # conv_transpose | bilinear
    norm: str = "batch"  # batch | group | none
    group_norm_groups: int = 8
    # TPU-first stem: 'none' = reference-parity full-resolution first level;
    # 's2d' = space-to-depth by ``stem_factor`` at the input with a subpixel
    # (depth-to-space) logit head — same task geometry, ~2.6× faster on TPU
    # because early convs run at r²× the channel count on 1/r² the pixels
    # (models/layers.py:space_to_depth).
    stem: str = "none"  # none | s2d
    stem_factor: int = 2
    # Full-resolution residual refinement after the subpixel head
    # (models/layers.py:DetailHead): two cheap full-res convs over
    # concat(logits, raw image) restore sub-stem_factor-px structure the
    # 1/r pyramid cannot carry.  Measured on the HardTiles stem A/B, where
    # plain s2d collapses the 2-6 px disc class.
    detail_head: bool = False
    # Which refinement architecture detail_head selects:
    # - 'fullres': two 3×3 convs at FULL resolution over concat(d2s logits,
    #   raw image) — pixel-translation-equivariant, but its low-channel
    #   full-res convs run lane-padded at 9-37 TF/s and its weight-gradient
    #   contractions over [B,H·W] dominated the round-3 step (docs/PERF.md
    #   roofline: ~43% of the flagship step in the head region);
    # - 's2d': the same residual refinement computed AT THE STEM GRID on the
    #   pre-d2s logits concat s2d(image) — channels (classes·r² + 3·r²) land
    #   in the MXU-efficient regime, weights are per-subpixel-phase (cell-
    #   level equivariance instead of pixel-level; strictly more parameters
    #   per FLOP), and no full-resolution activation exists in the head.
    detail_head_kind: str = "fullres"  # fullres | s2d
    # Hidden width of the refinement convs (round-3 shipped the only point
    # ever trained, 16; VERDICT r3 demanded the capacity sweep).
    detail_head_hidden: int = 16
    # Layout of the logits the model returns under train=True with an s2d
    # stem:
    # - 'fullres': depth_to_space to [B,H,W,classes] before the loss
    #   (round-3 behavior) — costs the d2s layout transpose plus loss/metric
    #   reductions over a 512² tensor whose last dim (classes) lane-pads
    #   ~20× on TPU;
    # - 'grouped': return the pre-d2s phase-major logits [B,H/r,W/r,r²·C];
    #   the train step groups the labels identically and computes the SAME
    #   per-pixel loss/metrics on the [..., r², C] view — bit-equal math
    #   (same multiset of (logit-row, label) pairs), no full-res tensor
    #   anywhere in the train graph.  Eval/predict always return full-res
    #   logits regardless.
    train_head_layout: str = "fullres"  # fullres | grouped
    # U-Net++ only: which logits the (shared) refinement head runs on.
    # - 'per_head': refine every deep-supervision head's logits (round-3
    #   behavior) — the refinement COMPUTE runs once per head, measured
    #   −43% throughput on the s2d×4 zoo row (678 → 383 tiles/s/chip);
    # - 'ensemble': supervision heads train unrefined; ONE refinement pass
    #   runs on the ensemble-mean readout, which joins the deep-supervision
    #   loss as an extra supervised output and is exactly the logits
    #   inference returns.  Refinement cost ×1 instead of ×(depth-1).
    detail_head_scope: str = "per_head"  # per_head | ensemble
    # Deep supervision heads for U-Net++.
    deep_supervision: bool = False
    # DeepLabV3+ specifics.
    output_stride: int = 16
    aspp_rates: Tuple[int, ...] = (6, 12, 18)
    compute_dtype: str = "bfloat16"  # dtype activations are computed in
    # Dtype of the logit head and the logits the model returns.  'float32'
    # is the conservative default; 'bfloat16' halves the HBM traffic of the
    # largest activation in the net ([B,H,W,C·r²] for subpixel heads and the
    # full-resolution logit upsample) — the loss/metrics cast to fp32 before
    # any softmax/reduction either way, so only logit *storage* rounds.
    head_dtype: str = "float32"  # float32 | bfloat16


@dataclass(frozen=True)
class DataConfig:
    """Tile-dataset pipeline.

    The reference eagerly loads a directory of images + ``.npy`` masks into
    RAM and crops 512×512 (кластер.py:660-674,737).  ``data_dir=None`` selects
    the synthetic generator (for tests/benchmarks without the ISPRS download).
    """

    data_dir: str | None = None
    dataset: str = "vaihingen"  # vaihingen | potsdam | cityscapes | synthetic
    image_size: Tuple[int, int] = (512, 512)  # (H, W)
    num_classes: int = 6
    test_split: int = 30  # last-N split, reference behavior (кластер.py:672-673)
    shuffle: bool = True  # reference computes a shuffle but never applies it (кластер.py:722-723)
    synthetic_len: int = 127  # reference trains on 127 tiles (кластер.py:720)
    seed: int = 0
    # > 0 switches to random-crop scene mode: the data_dir is read at native
    # scene size and each epoch draws this many image_size crops (the
    # many-crop generalization of the reference's fixed [:512,:512] crop,
    # кластер.py:817-823).  Evaluation uses a deterministic grid tiling of
    # the held-out scenes (capped at ``test_split`` tiles).
    crops_per_epoch: int = 0
    test_split_scenes: int = 1  # scenes held out for eval in crop mode
    # Fixed-tile mode: read tiles from disk per gather instead of stacking
    # the whole directory resident (~20 GB for full Cityscapes at
    # 512×1024).  The eval holdout stays eager (it is small by design and
    # prediction dumps need arrays).  Prefer prepare_* --format npy tiles
    # for decode-free reads; incompatible with device_cache.
    lazy_tiles: bool = False
    # Memory-map scene arrays instead of eager-loading them (crop mode
    # only): resident memory stays at the cropped pages, which is what
    # makes Potsdam-scale corpora (~25 GB eager) feasible.  Requires
    # array-format scenes (prepare_isprs.py --format npy); crops are
    # bit-identical to the eager path (tests/test_data.py).
    mmap_scenes: bool = False
    # Dihedral-group augmentation (4 rotations × optional flip) on training
    # tiles — standard for orientation-free aerial imagery; the reference
    # has none.  Requires square tiles; incompatible with device_cache
    # (augmentation happens in the host gather path).
    augment: bool = False
    # Ship bf16 images + int8 labels instead of fp32/int32 — through the
    # ShardedLoader host-upload path (44% of the wire bytes) or, under
    # device_cache, as the resident cache itself (44% of the cached HBM).
    # Numerically identical for this zoo's bf16-compute models — their
    # first conv casts inputs to bf16 regardless, and the loss clips/casts
    # labels itself (tests/test_data.py pins step-level bit-identity).
    # Requires num_classes <= 127.
    compact_upload: bool = False
    # Host-side threads for the ShardedLoader's gather/cast/upload
    # pipeline (SURVEY §7 hard part (c)): numpy's large copies/casts and
    # the device upload release the GIL, so >1 scales with cores on a pod
    # host.  Batch content and order are identical for any value.  NOTE:
    # the loader keeps max(prefetch, workers)+1 super-batches in flight
    # (workers below prefetch would idle), so workers above the default
    # prefetch=2 grow the number of UPLOADED batches resident in HBM —
    # budget accordingly on memory-tight configs.
    loader_workers: int = 1
    # Assemble super-batches with the native fused gather–cast–pack kernel
    # (csrc/batch.cc): one multithreaded memory pass writing straight into
    # the loader's preallocated buffer ring, instead of numpy's separate
    # single-threaded gather copy + astype copy + per-batch allocation.
    # Byte-identical to the numpy path (test-pinned).  When the kernel
    # cannot be built/loaded (no g++, no prebuilt csrc/libdwbatch.so) the
    # loader warns once and falls back to numpy — same discipline as the
    # wire codec.  ShardedLoader only; device_cache gathers on device.
    native_gather: bool = True
    # Upload the whole train set to HBM once and gather batches on device
    # (single-process, fixed-tile datasets that fit HBM — ISPRS scale is
    # ~0.5 GB).  Removes the per-epoch host→device re-upload, which on slow
    # host links costs more than the training compute (docs/PERF.md).
    device_cache: bool = False


@dataclass(frozen=True)
class TrainConfig:
    """Optimization loop.

    ``sync_period`` is the reference's ``frequency_sending_gradients``
    (кластер.py:685): micro-batches whose gradients are accumulated locally
    between synchronizations/optimizer steps.  ``micro_batch_size`` is the
    per-replica batch of one forward/backward (reference ``batch_size=1``,
    кластер.py:686).
    """

    epochs: int = 100
    micro_batch_size: int = 1
    sync_period: int = 50
    learning_rate: float = 1e-3  # torch.optim.Adam default, as the reference uses (кластер.py:704)
    optimizer: str = "adam"
    weight_decay: float = 0.0
    # Global-norm gradient clipping applied AFTER the cross-replica mean and
    # codec (every replica sees the identical gradient, so the clip factor
    # is identical too — replicated updates stay bit-identical).  0 = off,
    # the reference's behavior (no clipping anywhere).
    grad_clip_norm: float = 0.0
    # 'constant' (reference behavior: fixed default-LR Adam, кластер.py:704)
    # or 'cosine' (linear warmup over warmup_steps, cosine decay to 0 over
    # the run's total optimizer steps — the Trainer supplies the horizon).
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    seed: int = 0
    log_every_steps: int = 1
    checkpoint_every_epochs: int = 1
    keep_checkpoints: int = 3
    # Checkpoint subsystem (train/checkpoint.py, docs/CHECKPOINTS.md).
    # checkpoint_async hands the write (chunk → compress → fsync → prune)
    # to a background thread so the next epoch overlaps the I/O; the
    # training thread pays only the host snapshot, with a barrier on the
    # next save/exit and writer failures re-raised on the training thread
    # (train/async_checkpoint.py).
    checkpoint_async: bool = True
    # 'chunked' streams per-leaf bounded chunks through the DWZ1 codec
    # (no whole-state bytes copy; parallel save AND restore);
    # 'monolithic' is the legacy single-msgpack-blob writer.  Both restore
    # through the same reader regardless of this knob.
    checkpoint_format: str = "chunked"  # chunked | monolithic
    checkpoint_chunk_mb: int = 4  # raw MB per compression/IO unit
    # 'adaptive' probes each chunk and STORES entropy-dense fp32 weights
    # (~memcpy speed) while still deflating compressible tensors;
    # 'always' deflates everything at the wire level; 'store' never
    # deflates (fastest, largest).
    checkpoint_compression: str = "adaptive"  # adaptive | always | store
    eval_every_epochs: int = 1
    dump_images_per_epoch: int = 5  # qualitative PNG triples (кластер.py:785-790)
    # Rematerialize each micro-batch's forward during backward
    # (jax.checkpoint): ~1/3 more FLOPs for much lower peak activation HBM,
    # buying larger micro-batches on memory-bound models.  Known limit: the
    # U-Net++ dense grid rematerialized at 512² full width crashes the TPU
    # compiler (graph size); U-Net/DeepLab remat compile and run fine.
    remat: bool = False
    # Epoch index to capture an XLA profiler trace for (into
    # <workdir>/profile); -1 disables.  Replaces the reference's wall-clock
    # print "tracing" (SURVEY §5).
    profile_epoch: int = -1
    # Failure detection (the reference hangs forever on a dead peer,
    # кластер.py:215-220; SURVEY §5 "fault handling: none").  > 0 arms a
    # watchdog thread: if no train-loop heartbeat for this many seconds, it
    # dumps all thread stacks to stderr + <workdir>/stall.log and, with
    # stall_action='abort', exits (status 42) so a supervisor restarts the
    # job — which resumes from the latest checkpoint.  Size it well above a
    # first-compile + slowest-step bound; 0 disables.
    stall_timeout_s: float = 0.0
    stall_action: str = "dump"  # dump | abort
    # Preemption-graceful shutdown (ddlpc_tpu/resilience, docs/RESILIENCE.md).
    # On SIGTERM the trainer finishes the in-flight step, writes an
    # emergency checkpoint (mid-epoch position recorded, so the resume
    # skip-replays to the exact step and stays bit-identical with an
    # uninterrupted run), drains telemetry, and exits with status 43 —
    # which a supervisor treats as a clean restartable exit.  This is the
    # grace window: if the graceful path has not finished within it, the
    # process hard-exits (the last durable checkpoint still resumes).
    preempt_grace_s: float = 30.0
    # Unified telemetry (ddlpc_tpu/obs, docs/OBSERVABILITY.md).
    # trace=True arms the span tracer: per-phase spans (data wait, step
    # dispatch, loader gather/cast/upload, checkpoint, eval) stream to
    # <workdir>/spans.jsonl and a Perfetto-loadable <workdir>/trace.json.
    # Off (the default) the tracer is a no-op costing one attribute test
    # per would-be span.
    trace: bool = False
    # While tracing, block_until_ready on the step output every K steps so
    # spans measure REAL step latency at a sampled cadence without draining
    # the async dispatch pipeline on every step.  0 = never sync.
    trace_sync_every_steps: int = 16
    # >= 0 starts a stdlib HTTP telemetry endpoint on this port (0 =
    # ephemeral, for tests): GET /metrics (Prometheus text or JSON by
    # Accept header), /healthz (+ recent health alerts), /debug/trace
    # (arms the on-demand profiler).  -1 = off.  Process 0 only.
    telemetry_port: int = -1
    # Steps per on-demand profiler capture (SIGUSR2 or /debug/trace
    # without an explicit ?steps=N); the capture ends with a device sync
    # and aggregates into <workdir>/top_ops_NNN.json (obs/profiling.py).
    profile_steps: int = 20
    # Performance accounting (obs/flops.py, obs/comm.py, obs/hbm.py;
    # docs/PERF.md "Accounting").  On, the trainer computes the per-step
    # conv FLOP model once at start (a jaxpr trace, no compute) and
    # publishes live ddlpc_mfu / ddlpc_goodput / ddlpc_hbm_bytes /
    # ddlpc_comm_bytes_total on the telemetry endpoint, plus per-epoch
    # kind="perf"/"comm" JSONL records (scripts/perf_report.py renders
    # them).  Traced runs additionally sample a fenced comm-time probe
    # once per epoch on the trace_sync cadence.  Steady-state cost is a
    # few counter updates per optimizer step (measured inside PR 6's
    # <=2% traced-step bar).
    perf_accounting: bool = True
    # Peak FLOP/s per device for the MFU denominator; 0 = auto (device
    # kind lookup, falling back to the v5e peak with
    # ddlpc_peak_flops_assumed=1 so numbers stay comparable with the
    # committed bench tables).
    peak_flops_per_device: float = 0.0


@dataclass(frozen=True)
class ParallelConfig:
    """Device-mesh topology.

    Replaces the reference's L0–L4 socket stack (кластер.py:43-252): the data
    axis carries gradient all-reduce (the reference's parameter-server round
    trip), the space axis shards the spatial H dimension with halo exchange
    (the conv analog of sequence/context parallelism).
    ``data_axis_size=-1`` means "all remaining devices".
    """

    data_axis_size: int = -1
    space_axis_size: int = 1
    data_axis_name: str = "data"
    space_axis_name: str = "space"
    sync_batch_norm: bool = True  # reference lets BN stats drift per replica (SURVEY §3.1)
    # ZeRO cross-replica sharded update ladder (docs/SHARDING.md,
    # arxiv 2004.13336, 2204.06514).  Levels:
    # - 'zero1': full-mean all-reduce, then each replica updates only its
    #   1/N chunk of params+moments and all-gathers the fresh params.
    #   Optimizer-state HBM and update FLOPs divide by N; wire is 3·P per
    #   step.  Composes with EVERY codec transport (ring, pallas-mean).
    #   Trajectories match replicated to within FMA-contraction ulps — a
    #   declared, test-pinned deviation (parallel/train_step.py:
    #   _apply_update_zero1), far below any codec's quantization loss.
    # - 'zero2': the gradient sync IS a reduce-scatter (the fused wire
    #   already produces shards — zero2 stops all-gathering what it just
    #   scattered); gradients persist sharded 1/N, wire drops to 2·P.
    #   Bit-identical to the replicated update for every supported codec
    #   mode (test-pinned).  This is PR 5's program, renamed: what
    #   earlier revisions called "zero1" persisted scattered gradient
    #   shards and is ZeRO-2 in the paper's taxonomy.
    # - 'zero3': zero2, plus params persist as [N, K] chunks — the step
    #   all-gathers each leaf on demand for the forward/backward (freed
    #   after use) and never gathers at step end.  Params+grads+moments
    #   HBM all divide by N; the per-step params all-gather is the
    #   honest cost (bench.py --update-ab).  Bit-identical (test-pinned).
    # - 'auto' (default): 'zero2' for data meshes > 1, 'off' for
    #   singleton meshes and for the two codec combinations the scatter
    #   wire cannot reproduce bit-identically (transport='ring';
    #   codec_backend='pallas' with quantize_mean) — those compose with
    #   explicit 'zero1' instead.  'on' = 'zero2' but refuses those
    #   combinations loudly (parallel/shard_update.py:
    #   resolve_shard_update).  Checkpoints are layout-independent
    #   (always stored gathered); every layout restores from every
    #   other's blobs bit-identically.
    shard_update: str = "auto"  # auto | on | off | zero1 | zero2 | zero3
    # MPMD pipeline parallelism (docs/SHARDING.md "Pipeline stages",
    # arxiv 2412.14374): cut the encoder–decoder into `pipeline_stages`
    # contiguous block groups (parallel/partition.py stage rules) and map
    # each group onto its own (data, space) sub-mesh along a third `pipe`
    # mesh axis (parallel/mesh.py).  1 (default) = unstaged — the mesh
    # and every compiled program are bit-identical to pre-pipeline
    # revisions (test-pinned).  Values > 1 must divide the device count
    # after the space axis takes its share; the stage cut is chosen by
    # balanced per-block parameter bytes, so per-device resident
    # params+grads+moments shrink toward 1/stages (obs/hbm.py prices it,
    # bench.py --pipeline-ab measures it).
    pipeline_stages: int = 1
    # Microbatches per optimizer step under the GPipe round-robin
    # schedule (parallel/pipeline.py): the bubble fraction is
    # (S-1)/(M+S-1), so more microbatches amortize the fill/drain bubble
    # (кластер.py's 50-step gradient accumulation is exactly this stream).
    # 0 (default) resolves to `pipeline_stages` when staged; ignored at
    # pipeline_stages=1, where TrainConfig.sync_period already plays the
    # accumulation role.
    pipeline_microbatches: int = 0
    pipe_axis_name: str = "pipe"


@dataclass(frozen=True)
class CompressionConfig:
    """Lossy gradient codec — the reference's research contribution.

    ``mode`` mirrors ``model_bytes`` ∈ {'float32','float16','int8'}
    (кластер.py:25).  int8 uses ±``int8_levels`` integer levels
    (round(g/max*10), кластер.py:474); float16 uses ±``fp16_levels`` integer
    levels stored as fp16 (round(g/max*100), кластер.py:487).  Unlike the
    reference, 'float32'/'none' is a working identity path (its fp32 branch
    zeroes gradients, кластер.py:315,432) and max==0 cannot crash
    (кластер.py:345-396 NameError).

    ``quantize_local``: quantize each replica's gradient before the
    all-reduce (the worker→server wire, кластер.py:450-496).
    ``quantize_mean``: re-quantize the averaged gradient after the all-reduce
    so every replica applies bit-identical updates (the server's re-quantized
    broadcast + self-application trick, кластер.py:328-433).

    ``transport`` selects how the all-reduce moves bytes:
    - 'simulate' (default): exact fp32 `lax.pmean` with the codec's
      information loss injected around it — fastest within an ICI slice,
      where XLA's native collective wins;
    - 'ring': hand-written `ppermute` ring reduce-scatter/all-gather that
      puts the QUANTIZED values on the wire (int8 hops for the reference's
      ±10-level codec on ≤12 replicas) — 4× fewer interconnect bytes, the
      TPU-native realization of the reference's compressed TCP transport
      for bandwidth-bound DCN meshes (parallel/compressed_allreduce.py).
      Implies quantize_local+quantize_mean semantics with a shared scale.
    """

    mode: str = "none"  # none | int8 | float16
    int8_levels: int = 10
    fp16_levels: int = 100
    quantize_local: bool = True
    quantize_mean: bool = True
    transport: str = "simulate"  # simulate | ring
    # 'nearest' is the reference's deterministic round() (кластер.py:474,487).
    # 'stochastic' rounds up with probability equal to the fractional part:
    # E[quantized] == gradient, so the codec adds variance but no bias — the
    # standard fix for coarse-grid (int8, ±10 levels) convergence drag, which
    # the committed A/B measured for nearest (docs/QUANTIZATION.md).  The
    # noise is keyed off (TrainConfig.seed, replicated step counter) —
    # decorrelated per replica for the local quantization, shared for the
    # mean — so replicas stay bit-identical, same-seed runs replay the same
    # noise, and different seeds draw different noise.
    rounding: str = "nearest"  # nearest | stochastic
    # Which implementation runs the quantize→dequantize element work on the
    # simulate transport: 'xla' (default — traces show XLA fuses it to
    # ~bandwidth already, docs/PERF.md) or 'pallas' (fused single-pass TPU
    # kernel with hardware-PRNG stochastic rounding, ops/pallas_quantize.py).
    # The ring transport keeps its own inlined formula either way.
    codec_backend: str = "xla"  # xla | pallas
    # Comm/compute overlap: split the gradient tree into size-targeted
    # buckets (MiB of fp32 gradient per bucket, greedy over flatten order —
    # parallel/bucketing.py) and issue each bucket's fused quantized
    # collective separately, so backward compute of earlier layers can
    # overlap sync of later ones (the standard DDP trick the paper's
    # 50-microbatch accumulation was approximating).  0 (default) keeps
    # today's single whole-tree sync — bit-identical to pre-bucketing
    # programs.  Buckets quantize with per-bucket scales at both loss
    # points; simulate transport only (the ring's flatten/concat transport
    # is inherently whole-tree and rejects bucket_mb > 0).
    bucket_mb: float = 0.0


@dataclass(frozen=True)
class ServeConfig:
    """Inference serving engine (ddlpc_tpu/serve) — one artifact per deploy.

    Batching follows the dynamic micro-batching recipe from the serving
    literature (PAPERS.md: Gemma-on-TPU serving, pjit scaling): coalesce up
    to ``max_batch`` queued tiles or ``max_wait_ms`` from the oldest,
    whichever first.  ``queue_limit`` bounds admission — a submit beyond it
    is shed with a typed ``Overloaded`` error (fail fast, never queue
    unboundedly); ``deadline_ms`` expires requests that outlive their
    usefulness while queued (0 disables).
    """

    workdir: str = "runs/default"  # training run to restore + reload from
    host: str = "127.0.0.1"
    port: int = 8571
    max_batch: int = 8  # tiles coalesced into one forward
    max_wait_ms: float = 5.0  # max coalescing latency (coalesce batcher only)
    queue_limit: int = 64  # admission bound (tiles), then Overloaded
    deadline_ms: float = 2000.0  # per-request queue deadline; 0 = none
    # Admission loop (serve/cbatch.py vs serve/batching.py).
    # 'continuous' (default): ``slots`` worker threads each dispatch
    # whatever is queued the moment they free — no coalescing timer;
    # batching emerges from busy slots, and a freed slot refills the
    # device pipeline without draining.  'coalesce' is PR 1's
    # single-worker coalesce-and-wait MicroBatcher.
    batcher: str = "continuous"  # continuous | coalesce
    slots: int = 2  # concurrent in-flight forwards (continuous batcher)
    # Priority classes (continuous batcher): bulk tiling work
    # (?priority=batch) gets its own deep admission queue and a
    # starvation bound — at least one batch-class item is seated every
    # ``starvation_every``-th assembly — so it queues or sheds without
    # touching interactive p99.
    batch_queue_limit: int = 256  # bulk-class admission bound (tiles)
    starvation_every: int = 4
    # Weight quantization for the restored params (serve/quantized.py):
    # 'bf16' (shipped default — ½ the param HBM, within noise of fp32 on
    # the hard task) | 'int8' (¼ the HBM, per-leaf max-abs scales,
    # within 1 mIoU point on the hard task) | 'off'.  Scales are
    # computed once per restore/reload; dequant is fused into the jitted
    # forward (docs/SERVING.md "Continuous batching & quantized
    # inference").
    quantize: str = "bf16"  # off | int8 | bf16
    # Additionally cast input activations to bf16 inside the compiled
    # forward.  Off by default; enable only where the committed hard-task
    # table holds for your model (docs/SERVING.md).
    quantize_activations: bool = False
    overlap: float = 0.25  # sliding-window overlap for full scenes
    metrics_window: int = 2048  # latency ring size for p50/p95/p99
    metrics_every_s: float = 10.0  # periodic JSONL snapshot cadence; 0 = off
    # Span tracer for the request path (enqueue → coalesce → jit execute →
    # stitch): spans stream to <workdir>/serve_spans.jsonl and a Perfetto
    # trace to <workdir>/serve_trace.json (docs/OBSERVABILITY.md).
    trace: bool = False
    # Default batched forwards per /debug/trace profiler capture.
    profile_steps: int = 8
    # Graceful SIGTERM shutdown: max seconds to wait for in-flight HTTP
    # requests to finish writing their responses before exiting anyway.
    drain_timeout_s: float = 30.0
    # Where serve_metrics.jsonl (and traces) land; "" = workdir.  The
    # fleet gives each replica its own dir so N processes never interleave
    # one JSONL stream.
    metrics_dir: str = ""

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ServeConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"unknown config key ServeConfig.{sorted(unknown)[0]}"
            )
        return cls(**d)

    @classmethod
    def from_json(cls, s: str) -> "ServeConfig":
        return cls.from_dict(json.loads(s))

    def replace(self, **kwargs) -> "ServeConfig":
        return dataclasses.replace(self, **kwargs)


@dataclass(frozen=True)
class FleetConfig:
    """Fault-tolerant serving fleet (serve/fleet.py + serve/router.py).

    One router process supervises ``replicas`` engine subprocesses (each a
    ``python -m ddlpc_tpu.serve.server`` on an ephemeral port) and
    dispatches tiles by per-replica health and occupancy, with per-request
    timeout → retry-on-another-replica (full-jitter backoff), hedged
    requests for the tail, and a per-replica circuit breaker.  Rolling
    hot-reload pushes a new checkpoint replica-by-replica
    (drain → /reload → warmup → readmit) and falls back fleet-wide if any
    replica's reload quarantines the blob (docs/SERVING.md "Fleet").
    """

    workdir: str = "runs/default"  # training run every replica serves
    # Router/supervisor state dir (replica logs + port files, router.jsonl);
    # "" = <workdir>/fleet.
    fleet_dir: str = ""
    host: str = "127.0.0.1"
    port: int = 8570  # router HTTP port (0 = ephemeral)
    replicas: int = 3
    # Per-replica serve knobs, forwarded into each replica's ServeConfig.
    max_batch: int = 8
    max_wait_ms: float = 5.0
    queue_limit: int = 64
    deadline_ms: float = 2000.0
    overlap: float = 0.25
    batcher: str = "continuous"  # continuous | coalesce (serve/cbatch.py)
    slots: int = 2
    batch_queue_limit: int = 256
    starvation_every: int = 4
    quantize: str = "bf16"  # off | int8 | bf16 (serve/quantized.py)
    quantize_activations: bool = False
    # Router-side bulk shedding: when EVERY eligible replica's scraped
    # interactive queue depth is at or above this, ?priority=batch
    # requests are shed with a 503 at the router (interactive traffic is
    # never shed by this rule).  0 disables.
    batch_shed_queue_depth: int = 0
    # Bounded wait at admission when ZERO replicas are eligible: a
    # rolling reload's drain→readmit hand-off, a relaunch-readiness gap,
    # and a breaker cooldown can momentarily coincide — transient
    # total-outage blips that should surface as tail latency, not
    # client-visible 503s.  A request that still finds no replica after
    # this wait gets the 503.  0 = fail fast.
    no_replica_wait_ms: float = 1000.0
    # Dispatch: per-attempt replica timeout; a timed-out/failed attempt
    # retries on a DIFFERENT replica up to ``retries`` times with
    # full-jitter backoff; after ``hedge_ms`` without a response a
    # duplicate is hedged to a second replica (first answer wins, the
    # loser is cancelled).  0 disables hedging.
    request_timeout_ms: float = 4000.0
    retries: int = 2
    retry_backoff_ms: float = 25.0
    hedge_ms: float = 1000.0
    hedge_max: int = 1
    # Per-replica circuit breaker: error rate over the last
    # ``breaker_window`` outcomes (once ``breaker_min_samples`` seen)
    # >= ``breaker_error_rate`` opens the circuit; after
    # ``breaker_cooldown_s`` it half-opens and admits
    # ``breaker_half_open_probes`` probes; ``breaker_close_after``
    # consecutive probe successes re-close it, any probe failure re-opens.
    breaker_window: int = 16
    breaker_min_samples: int = 8
    breaker_error_rate: float = 0.5
    breaker_cooldown_s: float = 2.0
    breaker_half_open_probes: int = 1
    breaker_close_after: int = 2
    # Health scraping (one cheap /healthz per replica per interval):
    # ``unhealthy_after`` consecutive failed scrapes take a replica out of
    # dispatch until a scrape succeeds again.
    scrape_every_s: float = 1.0
    scrape_timeout_s: float = 2.0
    unhealthy_after: int = 3
    # Drain / rolling reload.
    drain_timeout_s: float = 30.0
    warmup_timeout_s: float = 180.0  # replica readiness deadline per (re)launch
    # Replica supervision (resilience/supervisor.py RestartPolicy).
    max_restarts: int = 100
    crash_loop_limit: int = 3
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 30.0
    metrics_every_s: float = 10.0  # router.jsonl snapshot cadence; 0 = off
    # Distributed tracing (docs/OBSERVABILITY.md "Distributed tracing"):
    # the router mints one W3C-style trace id per request, records
    # route_request/router_attempt spans to <fleet_dir>/router_spans.jsonl,
    # and forwards the context to the replica on the traceparent header;
    # replicas (which inherit this knob via replica_serve_config) stamp it
    # into their serve_request spans, so obs/merge.py can stitch the
    # per-process streams into ONE fleet timeline.
    trace: bool = False
    # Fleet telemetry aggregation (obs/aggregate.py): the front end
    # scrapes every replica's /metrics (plus the router's own registry)
    # every ``aggregate_every_s`` into ddlpc_fleet_* rollups on the fleet
    # /metrics; a source whose last successful scrape is older than
    # ``aggregate_stale_after_s`` is flagged stale and dropped from the
    # rollups (its last per-replica series stay visible).  0 = off.
    aggregate_every_s: float = 2.0
    aggregate_stale_after_s: float = 15.0
    # SLO layer (obs/health.py:SLOTracker): a routed request is GOOD when
    # it succeeds (no 5xx) within its class's latency objective; the
    # availability objective says what fraction must be good.  Burn-rate
    # alerts fire on two windows (fast = page-grade outage, slow = budget
    # leak), latched like every other health detector; error budgets and
    # burn rates ride the fleet /healthz and kind="slo" records on
    # router.jsonl.
    slo_enabled: bool = True
    slo_interactive_p99_ms: float = 1000.0  # latency objective per class
    slo_batch_p99_ms: float = 10000.0
    slo_availability: float = 0.999  # good-request fraction objective
    slo_budget_window_s: float = 3600.0  # error-budget accounting window
    slo_fast_window_s: float = 300.0
    slo_fast_burn: float = 14.0  # burn-rate threshold (critical)
    slo_slow_window_s: float = 3600.0
    slo_slow_burn: float = 2.0  # burn-rate threshold (warn)
    # Elastic fleet (serve/autoscale.py; docs/SERVING.md "Elastic fleet"):
    # a policy loop scales the replica count between the min/max bounds on
    # SLO burn rate, interactive queue depth, and slot-busy fraction, with
    # a cooldown between actions so it never flaps.  Scale-up triggers
    # when ANY high-water mark is crossed; scale-down requires EVERY
    # signal under its low-water mark (and burn rate < 1.0).
    autoscale_enabled: bool = False
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 8
    autoscale_interval_s: float = 2.0  # policy evaluation cadence
    autoscale_cooldown_s: float = 30.0  # min seconds between actions
    autoscale_burn_threshold: float = 2.0  # interactive fast-window burn
    autoscale_queue_depth_high: float = 8.0  # mean interactive queue/replica
    autoscale_queue_depth_low: float = 1.0
    autoscale_slot_busy_high: float = 0.85  # max replica slot-busy fraction
    autoscale_slot_busy_low: float = 0.30
    # Content-addressed response cache (serve/cache.py): the router
    # answers repeated tiles from memory, keyed by sha256(input bytes +
    # serving step + quant mode), LRU-bounded by payload bytes and
    # invalidated fleet-wide whenever the serving step changes.  0 = off;
    # ?cache=bypass skips it per request.
    cache_max_bytes: int = 0

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FleetConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"unknown config key FleetConfig.{sorted(unknown)[0]}"
            )
        return cls(**d)

    @classmethod
    def from_json(cls, s: str) -> "FleetConfig":
        return cls.from_dict(json.loads(s))

    def replace(self, **kwargs) -> "FleetConfig":
        return dataclasses.replace(self, **kwargs)

    def resolved_fleet_dir(self) -> str:
        return self.fleet_dir or os.path.join(self.workdir, "fleet")

    def replica_serve_config(self, metrics_dir: str = "") -> "ServeConfig":
        """The ServeConfig one replica subprocess runs with."""
        return ServeConfig(
            workdir=self.workdir,
            host=self.host,
            port=0,  # ephemeral; the supervisor reads the port file
            max_batch=self.max_batch,
            max_wait_ms=self.max_wait_ms,
            queue_limit=self.queue_limit,
            deadline_ms=self.deadline_ms,
            overlap=self.overlap,
            batcher=self.batcher,
            slots=self.slots,
            batch_queue_limit=self.batch_queue_limit,
            starvation_every=self.starvation_every,
            quantize=self.quantize,
            quantize_activations=self.quantize_activations,
            drain_timeout_s=self.drain_timeout_s,
            metrics_dir=metrics_dir,
            # Trace context crosses the process boundary only if the
            # replica traces too (spans land in ITS metrics_dir).
            trace=self.trace,
        )


@dataclass(frozen=True)
class ExperimentConfig:
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    compression: CompressionConfig = field(default_factory=CompressionConfig)
    workdir: str = "runs/default"

    # ---- (de)serialization -------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ExperimentConfig":
        def build(klass, sub):
            fields = {f.name: f for f in dataclasses.fields(klass)}
            kwargs = {}
            for k, v in sub.items():
                if k not in fields:
                    raise ValueError(f"unknown config key {klass.__name__}.{k}")
                if isinstance(v, list):
                    v = tuple(v)
                kwargs[k] = v
            return klass(**kwargs)

        return cls(
            model=build(ModelConfig, d.get("model", {})),
            data=build(DataConfig, d.get("data", {})),
            train=build(TrainConfig, d.get("train", {})),
            parallel=build(ParallelConfig, d.get("parallel", {})),
            compression=build(CompressionConfig, d.get("compression", {})),
            workdir=d.get("workdir", "runs/default"),
        )

    @classmethod
    def from_json(cls, s: str) -> "ExperimentConfig":
        return cls.from_dict(json.loads(s))

    def replace(self, **kwargs) -> "ExperimentConfig":
        return dataclasses.replace(self, **kwargs)
