"""ddlpc_tpu — TPU-native distributed segmentation training framework.

A ground-up JAX/XLA re-design of the capabilities of
``NikolayKrivosheev/Distributed-deep-learning-on-personal-computers``
(reference: single-file PyTorch script ``Vaihingen PyTorch 2 (кластер).py``):
synchronous data-parallel training of convolutional segmentation models with
gradient accumulation and optional lossy (max-abs int8 / fp16) gradient
compression.  Where the reference hand-rolls a TCP parameter-server star
(кластер.py:105-252), this framework uses a `jax.sharding.Mesh` with XLA
collectives over ICI/DCN; where the reference is one hostname-keyed script,
this is a package with a config system, sharded data pipeline, checkpointing,
metrics (incl. mIoU) and tests.

Package layout
--------------
- ``models/``   Flax NHWC model zoo: U-Net, U-Net++, DeepLabV3+.
- ``ops/``      Losses, metrics, the gradient quantization codec, Pallas kernels.
- ``parallel/`` Mesh construction, shard_map train/eval steps, halo exchange.
- ``data/``     Tile datasets (Vaihingen/Potsdam/Cityscapes-style), host sharding.
- ``train/``    Trainer driver, checkpointing, logging/observability.
- ``utils/``    Wire codec (C++-backed compression), misc.
"""

__version__ = "0.1.0"

from ddlpc_tpu.config import (  # noqa: F401
    CompressionConfig,
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    ParallelConfig,
    TrainConfig,
)
