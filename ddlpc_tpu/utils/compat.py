"""Version-portability shims for the pinned jax toolchain.

The repo targets current jax idiom (``jax.shard_map``), but the container
pins jax 0.4.37, where shard_map still lives in ``jax.experimental`` and the
replication-checking kwarg is ``check_rep`` (renamed ``check_vma`` when the
API was promoted).  Callers import :func:`shard_map` from here and pass the
portable ``check`` kwarg; the shim resolves whichever API is installed.
"""

from __future__ import annotations

from typing import Any

import jax


def force_cpu_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU backend, portably.

    Newer jax exposes the ``jax_num_cpu_devices`` config option; older ones
    (the pinned 0.4.37) only honor ``--xla_force_host_platform_device_count``
    in ``XLA_FLAGS``, which is read when the CPU client initializes — so
    this must be called BEFORE the first device use (it is fine to call it
    after ``import jax``).
    """
    import os

    # REPLACE any pre-existing count rather than skip: a child process
    # inheriting the parent's XLA_FLAGS (e.g. conftest's 8) must still get
    # the count IT asked for, or its run is silently mislabeled.
    kept = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:
        pass  # pre-0.5 jax: the XLA_FLAGS fallback already took effect


def axis_size(axis_name: str) -> int:
    """Static size of a named mapped axis, inside shard_map/pmap.

    ``lax.axis_size`` only exists on newer jax; the portable idiom
    ``lax.psum(1, axis)`` constant-folds to a Python int on the old ones.
    """
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map(
    f: Any, *, mesh: Any, in_specs: Any, out_specs: Any, check: bool = True
) -> Any:
    """`jax.shard_map` on new jax, `jax.experimental.shard_map` on old.

    ``check`` maps to ``check_vma`` (new) / ``check_rep`` (old) — both gate
    the same per-output replication verification.  The kwarg is picked by
    inspecting the resolved function's signature, not by which module it
    came from: there are release bands where the top-level API still took
    ``check_rep``.
    """
    import inspect

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn  # type: ignore

    try:
        params = inspect.signature(fn).parameters
        kwarg = "check_vma" if "check_vma" in params else "check_rep"
    except (TypeError, ValueError):  # C-level or wrapped: assume modern
        kwarg = "check_vma" if hasattr(jax, "shard_map") else "check_rep"
    return fn(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{kwarg: check}
    )
