"""Atomic, durable small-file writes — the one helper for report JSONs.

``train/checkpoint.py`` owns the heavyweight chunked-blob write path and
``resilience/protocol.py`` owns breadcrumbs; everything else in the repo
that drops a small JSON (bench reports, perf-gate baselines, analyzer
summaries) goes through here.  The discipline is the same everywhere:
write to a temp file in the destination directory, fsync, then
``os.replace`` — a crash mid-write can never leave a torn or empty file
where a reader (or a committed artifact) expects a whole one.

``scripts/ddlpc_check.py``'s ``atomic-write`` rule flags bare
``open(path, "w")`` + ``json.dump`` emit sites and points here.

Pure stdlib (no jax, no numpy) so every tier can import it.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Optional


def atomic_write_text(
    path: str,
    text: str,
    durable: bool = True,
    fsync_dir: bool = False,
) -> str:
    """Write ``text`` to ``path`` via tmp + fsync + rename; returns path.

    ``durable=False`` skips the file fsync (keeping only rename
    atomicity) — for frequently-rewritten advisory files on hot paths:
    fsync costs ~50 ms on containerized filesystems, so a per-epoch
    caller must opt out explicitly and own the argument.  ``fsync_dir=
    True`` additionally fsyncs the containing directory so the RENAME
    itself survives a power loss (the checkpoint-grade guarantee);
    report JSONs normally skip it — one dirent is not worth a directory
    sync per bench row.
    """
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        # mkstemp creates 0600; restore the umask-default mode so the
        # rename can't silently tighten permissions on reports/baselines
        # that other uids (artifact collectors, scrapers) read.
        umask = os.umask(0)
        os.umask(umask)
        os.fchmod(fd, 0o666 & ~umask)
        with os.fdopen(fd, "w") as f:
            f.write(text)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    if fsync_dir:
        dir_fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    return path


def atomic_write_json(
    path: str,
    obj: Any,
    indent: Optional[int] = 2,
    durable: bool = True,
    fsync_dir: bool = False,
) -> str:
    """``json.dump`` with the tmp + fsync + rename discipline."""
    return atomic_write_text(
        path,
        json.dumps(obj, indent=indent) + "\n",
        durable=durable,
        fsync_dir=fsync_dir,
    )
