"""Deadline-bounded backend probe — ONE implementation for every harness.

A wedged device tunnel (observed rounds 4-5: the axon relay dies and every
subsequent ``jax.devices()`` blocks FOREVER) must never hang a harness
silently.  bench.py, ``__graft_entry__.entry()`` and
``__graft_entry__.dryrun_multichip()`` all need the same probe with
different deadlines and different failure policies (error line / raise /
virtual-CPU fallback); they share this helper so deadline and grace
tuning happen in one place.
"""

from __future__ import annotations

import threading
from typing import Sequence, Union

import jax

ProbeResult = Union[Sequence[jax.Device], Exception, None]

GRACE_S = 5.0  # default post-deadline re-check window


def probe_bound_s(deadline_s: float, grace_s: float = GRACE_S) -> float:
    """The WORST-CASE wall time :func:`probe_backend` may block: the
    deadline plus the grace re-check.  Callers reporting "timed out after
    N s" must use this bound, not ``deadline_s`` alone — the messages
    previously under-reported the wait by ``grace_s`` (ADVICE r5)."""
    return deadline_s + (grace_s if grace_s > 0 else 0.0)


def probe_backend(deadline_s: float, grace_s: float = GRACE_S) -> ProbeResult:
    """``jax.devices()`` with a deadline, off-thread.

    Returns the device list on success, the raised ``Exception`` on init
    failure, or ``None`` if init was still blocked after ``deadline_s``
    (+ one ``grace_s`` re-check, because the daemon thread may finish init
    just after the deadline — the probe is advisory, not a cancellation).
    The total blocking bound is therefore :func:`probe_bound_s`, which is
    what any user-facing timeout message should quote.
    The probing thread is a daemon: a hung init cannot keep the process
    alive, but it may complete concurrently after this returns.
    """
    probed: list = []

    def _probe() -> None:
        try:
            probed.append(jax.devices())
        except Exception as e:  # noqa: BLE001 — callers choose the policy
            probed.append(e)

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(deadline_s)
    if not probed and grace_s > 0:
        t.join(grace_s)
    return probed[0] if probed else None
