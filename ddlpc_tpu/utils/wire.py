"""Wire codec: block-parallel deflate compression + length-prefixed framing.

TPU-native replacement for the reference's L0/L1 stack (кластер.py:43-102):
``parallel_compress`` = pickle + mgzip(level=1, threads=12, blocksize=1e6)
and 4-byte big-endian length framing.  Differences by design:

- No pickle for untrusted payloads: the codec moves *bytes*; callers decide
  the serialization (checkpoints use flax msgpack, train/checkpoint.py).
- Block format: the payload is split into fixed blocks, each deflated
  independently, so compression AND decompression parallelize (mgzip only
  parallelizes compression; its decompression is serial).
- The hot path is a C++ kernel (csrc/wire.cc) driving zlib across a thread
  pool, loaded via ctypes; a pure-Python zlib fallback (threaded — zlib
  releases the GIL on large buffers) keeps the API available everywhere.

Frame layout (little-endian):
  magic  4B  b"DWZ1"
  nblk   u32 number of blocks
  per block: raw_len u32, comp_len u32, comp bytes
Message framing (pack_message): u32 payload length + payload — the
reference's '>I' prefix (кластер.py:119) kept for tooling compatibility,
in LE to match the block format.
"""

from __future__ import annotations

import concurrent.futures
import os
import struct
import zlib
from typing import Optional

MAGIC = b"DWZ1"
BLOCK_SIZE = 1 << 20  # 1 MiB, the reference's mgzip blocksize (кластер.py:51)
LEVEL = 1  # the reference's compresslevel (кластер.py:51)
_MAX_WORKERS = min(12, os.cpu_count() or 1)  # reference thread=12

_native = None  # set by utils.native when the C++ library is built/loaded


def _get_native():
    global _native
    if _native is None:
        try:
            from ddlpc_tpu.utils import native

            _native = native.load() or False
        except Exception:
            _native = False
    return _native or None


_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None


def _get_pool() -> concurrent.futures.ThreadPoolExecutor:
    global _pool
    if _pool is None:
        _pool = concurrent.futures.ThreadPoolExecutor(_MAX_WORKERS)
    return _pool


def compress(data: bytes, level: int = LEVEL, block_size: int = BLOCK_SIZE) -> bytes:
    """Frame + deflate ``data`` in parallel blocks."""
    native = _get_native()
    if native is not None:
        return native.compress(data, level, block_size)
    view = memoryview(data)
    blocks = [view[i : i + block_size] for i in range(0, len(data), block_size)]
    if len(blocks) <= 1:
        comps = [zlib.compress(b, level) for b in blocks]
    else:
        comps = list(_get_pool().map(lambda b: zlib.compress(b, level), blocks))
    out = [MAGIC, struct.pack("<I", len(blocks))]
    for raw, comp in zip(blocks, comps):
        out.append(struct.pack("<II", len(raw), len(comp)))
        out.append(comp)
    return b"".join(out)


def decompress(data: bytes) -> bytes:
    """Inverse of :func:`compress`; blocks decompressed in parallel."""
    native = _get_native()
    if native is not None:
        return native.decompress(data)
    if len(data) < 4:
        raise ValueError("truncated frame: missing magic")
    if data[:4] != MAGIC:
        raise ValueError("bad wire magic; not a DWZ1 frame")
    if len(data) < 8:
        raise ValueError("truncated frame: missing block count")
    (nblk,) = struct.unpack_from("<I", data, 4)
    if nblk > (len(data) - 8) // 8:
        raise ValueError("truncated frame: block count exceeds frame size")
    off = 8
    metas = []
    for _ in range(nblk):
        if off + 8 > len(data):
            raise ValueError("truncated frame: missing block header")
        raw_len, comp_len = struct.unpack_from("<II", data, off)
        off += 8
        if off + comp_len > len(data):
            raise ValueError("truncated frame: missing block payload")
        # Deflate cannot expand beyond ~1032:1; a header claiming more is
        # forged (mirrors the C++ decoder's bound).
        if raw_len > comp_len * 1040 + 1024:
            raise ValueError(
                f"corrupt frame: block claims {raw_len} bytes from {comp_len}"
            )
        metas.append((raw_len, data[off : off + comp_len]))
        off += comp_len
    if off != len(data):
        raise ValueError(f"trailing garbage in frame: {len(data) - off} bytes")

    def one(meta):
        raw_len, comp = meta
        # Cap the inflate at the header's claimed size (+1 to detect excess)
        # so a deflate-bomb block cannot allocate more than the header
        # admits to — the header itself is bounded against the frame above.
        d = zlib.decompressobj()
        raw = d.decompress(comp, raw_len + 1)
        if len(raw) != raw_len or not d.eof or d.unused_data:
            raise ValueError(
                f"block decompressed to {len(raw)}{'+' if not d.eof else ''}, "
                f"header says {raw_len}"
            )
        return raw

    if nblk <= 1:
        raws = [one(m) for m in metas]
    else:
        raws = list(_get_pool().map(one, metas))
    return b"".join(raws)


def pack_message(payload: bytes) -> bytes:
    """Length-prefix a payload (the reference's framing, кластер.py:119)."""
    return struct.pack("<I", len(payload)) + payload


def unpack_message(buf: bytes) -> tuple[bytes, bytes]:
    """(payload, rest) from a length-prefixed buffer; raises if truncated."""
    if len(buf) < 4:
        raise ValueError("truncated frame: missing length prefix")
    (n,) = struct.unpack_from("<I", buf, 0)
    if len(buf) < 4 + n:
        raise ValueError(f"truncated frame: need {n} payload bytes, have {len(buf) - 4}")
    return bytes(buf[4 : 4 + n]), bytes(buf[4 + n :])
