"""Wire codec: block-parallel deflate compression + length-prefixed framing.

TPU-native replacement for the reference's L0/L1 stack (кластер.py:43-102):
``parallel_compress`` = pickle + mgzip(level=1, threads=12, blocksize=1e6)
and 4-byte big-endian length framing.  Differences by design:

- No pickle for untrusted payloads: the codec moves *bytes*; callers decide
  the serialization (checkpoints use flax msgpack, train/checkpoint.py).
- Block format: the payload is split into fixed blocks, each deflated
  independently, so compression AND decompression parallelize (mgzip only
  parallelizes compression; its decompression is serial).
- The hot path is a C++ kernel (csrc/wire.cc) driving zlib across a thread
  pool, loaded via ctypes; a pure-Python zlib fallback (threaded — zlib
  releases the GIL on large buffers) keeps the API available everywhere.

Frame layout (little-endian):
  magic  4B  b"DWZ1"
  nblk   u32 number of blocks
  per block: raw_len u32, comp_len u32, comp bytes
Message framing (pack_message): u32 payload length + payload — the
reference's '>I' prefix (кластер.py:119) kept for tooling compatibility,
in LE to match the block format.
"""

from __future__ import annotations

import concurrent.futures
import os
import struct
import zlib
from typing import Optional

MAGIC = b"DWZ1"
BLOCK_SIZE = 1 << 20  # 1 MiB, the reference's mgzip blocksize (кластер.py:51)
LEVEL = 1  # the reference's compresslevel (кластер.py:51)
_MAX_WORKERS = min(12, os.cpu_count() or 1)  # reference thread=12

_native = None  # set by utils.native when the C++ library is built/loaded


def _get_native():
    global _native
    if _native is None:
        try:
            from ddlpc_tpu.utils import native

            _native = native.load() or False
        except Exception:
            _native = False
    return _native or None


_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None


def _get_pool() -> concurrent.futures.ThreadPoolExecutor:
    global _pool
    if _pool is None:
        _pool = concurrent.futures.ThreadPoolExecutor(_MAX_WORKERS)
    return _pool


def compress(data: bytes, level: int = LEVEL, block_size: int = BLOCK_SIZE) -> bytes:
    """Frame + deflate ``data`` in parallel blocks."""
    native = _get_native()
    if native is not None:
        return native.compress(data, level, block_size)
    view = memoryview(data)
    blocks = [view[i : i + block_size] for i in range(0, len(data), block_size)]
    if len(blocks) <= 1:
        comps = [zlib.compress(b, level) for b in blocks]
    else:
        comps = list(_get_pool().map(lambda b: zlib.compress(b, level), blocks))
    out = [MAGIC, struct.pack("<I", len(blocks))]
    for raw, comp in zip(blocks, comps):
        out.append(struct.pack("<II", len(raw), len(comp)))
        out.append(comp)
    return b"".join(out)


def decompress(data: bytes) -> bytes:
    """Inverse of :func:`compress`; blocks decompressed in parallel."""
    native = _get_native()
    if native is not None:
        return native.decompress(data)
    if len(data) < 4:
        raise ValueError("truncated frame: missing magic")
    if data[:4] != MAGIC:
        raise ValueError("bad wire magic; not a DWZ1 frame")
    if len(data) < 8:
        raise ValueError("truncated frame: missing block count")
    (nblk,) = struct.unpack_from("<I", data, 4)
    if nblk > (len(data) - 8) // 8:
        raise ValueError("truncated frame: block count exceeds frame size")
    off = 8
    metas = []
    for _ in range(nblk):
        if off + 8 > len(data):
            raise ValueError("truncated frame: missing block header")
        raw_len, comp_len = struct.unpack_from("<II", data, off)
        off += 8
        if off + comp_len > len(data):
            raise ValueError("truncated frame: missing block payload")
        # Deflate cannot expand beyond ~1032:1; a header claiming more is
        # forged (mirrors the C++ decoder's bound).
        if raw_len > comp_len * 1040 + 1024:
            raise ValueError(
                f"corrupt frame: block claims {raw_len} bytes from {comp_len}"
            )
        metas.append((raw_len, data[off : off + comp_len]))
        off += comp_len
    if off != len(data):
        raise ValueError(f"trailing garbage in frame: {len(data) - off} bytes")

    def one(meta):
        raw_len, comp = meta
        # Cap the inflate at the header's claimed size (+1 to detect excess)
        # so a deflate-bomb block cannot allocate more than the header
        # admits to — the header itself is bounded against the frame above.
        d = zlib.decompressobj()
        raw = d.decompress(comp, raw_len + 1)
        if len(raw) != raw_len or not d.eof or d.unused_data:
            raise ValueError(
                f"block decompressed to {len(raw)}{'+' if not d.eof else ''}, "
                f"header says {raw_len}"
            )
        return raw

    if nblk <= 1:
        raws = [one(m) for m in metas]
    else:
        raws = list(_get_pool().map(one, metas))
    return b"".join(raws)


def decompress_into(data: bytes, out: memoryview) -> int:
    """Inflate a DWZ1 frame directly into ``out`` (a writable uint8 view);
    returns the byte count written.  Block-parallel like :func:`decompress`
    but without materializing the joined bytes object — the chunked
    checkpoint reader inflates every chunk straight into its leaf's
    preallocated buffer slice (train/checkpoint.py)."""
    native = _get_native()
    if native is not None:
        raw = native.decompress(data)
        if len(raw) > len(out):
            raise ValueError(
                f"frame inflates to {len(raw)} bytes, buffer holds {len(out)}"
            )
        out[: len(raw)] = raw
        return len(raw)
    if len(data) < 8 or data[:4] != MAGIC:
        raise ValueError("truncated or non-DWZ1 frame")
    (nblk,) = struct.unpack_from("<I", data, 4)
    if nblk > (len(data) - 8) // 8:
        raise ValueError("truncated frame: block count exceeds frame size")
    off = 8
    jobs = []  # (raw_offset, raw_len, comp bytes)
    raw_off = 0
    for _ in range(nblk):
        if off + 8 > len(data):
            raise ValueError("truncated frame: missing block header")
        raw_len, comp_len = struct.unpack_from("<II", data, off)
        off += 8
        if off + comp_len > len(data):
            raise ValueError("truncated frame: missing block payload")
        if raw_len > comp_len * 1040 + 1024:
            raise ValueError(
                f"corrupt frame: block claims {raw_len} bytes from {comp_len}"
            )
        jobs.append((raw_off, raw_len, data[off : off + comp_len]))
        raw_off += raw_len
        off += comp_len
    if off != len(data):
        raise ValueError(f"trailing garbage in frame: {len(data) - off} bytes")
    if raw_off > len(out):
        raise ValueError(
            f"frame inflates to {raw_off} bytes, buffer holds {len(out)}"
        )

    def one(job):
        dst, raw_len, comp = job
        d = zlib.decompressobj()
        raw = d.decompress(comp, raw_len + 1)
        if len(raw) != raw_len or not d.eof or d.unused_data:
            raise ValueError(
                f"block decompressed to {len(raw)}{'+' if not d.eof else ''}, "
                f"header says {raw_len}"
            )
        out[dst : dst + raw_len] = raw

    if nblk <= 1:
        for j in jobs:
            one(j)
    else:
        list(_get_pool().map(one, jobs))
    return raw_off


def probe_level(
    sample, level: int = LEVEL, threshold: float = 0.85, probe_bytes: int = 1 << 16
) -> int:
    """Adaptive level policy for entropy-dense payloads: deflate a small
    prefix of ``sample``; if it barely shrinks (ratio > ``threshold``),
    return 0 — zlib *stored* blocks, ~memcpy speed — else ``level``.

    Trained fp32 weights are mantissa-noise and compress only ~7% at
    level 1 (measured: ratio 0.927 on N(0, 0.05²) float32) while costing
    most of a checkpoint's wall clock; zeroed or quantized tensors
    compress 3-200×.  The 0.85 default means "store unless deflate saves
    at least 15%" — the break-even where burning a core beats the disk.
    Deciding per chunk keeps both regimes fast and the output is a valid
    deflate stream either way, so every existing DWZ1 reader (native and
    Python) inflates it unchanged."""
    probe = bytes(memoryview(sample)[:probe_bytes])
    if not probe:
        return level
    return 0 if len(zlib.compress(probe, level)) > threshold * len(probe) else level


_stream_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None


def _get_stream_pool() -> concurrent.futures.ThreadPoolExecutor:
    # Distinct from _pool on purpose: stream tasks call compress(), which
    # fans blocks out onto _pool and WAITS — running those waiting tasks on
    # _pool itself could deadlock with every slot occupied by a waiter.
    global _stream_pool
    if _stream_pool is None:
        _stream_pool = concurrent.futures.ThreadPoolExecutor(
            2, thread_name_prefix="wire-stream"
        )
    return _stream_pool


def compress_chunks(chunks, level: int = LEVEL, block_size: int = BLOCK_SIZE,
                    window: int = 2, adaptive: bool = False):
    """Compress an iterable of independent payloads into DWZ1 frames,
    yielding them strictly in input order while up to ``window`` future
    chunks compress in the background — the producer/consumer overlap that
    lets a writer stream frames to disk while the next chunks deflate.
    ``level`` may be a callable ``chunk -> level`` (e.g. a bound
    :func:`probe_level`) or, with ``adaptive=True``, the per-chunk stored
    vs deflate decision is made here."""

    def job(chunk):
        lv = level(chunk) if callable(level) else level
        if adaptive and not callable(level):
            lv = probe_level(chunk, lv)
        return compress(bytes(chunk), lv, block_size)

    pool = _get_stream_pool()
    pending: list = []
    it = iter(chunks)
    try:
        for chunk in it:
            pending.append(pool.submit(job, chunk))
            while len(pending) > window:
                yield pending.pop(0).result()
        while pending:
            yield pending.pop(0).result()
    finally:
        for f in pending:
            f.cancel()


def pack_message(payload: bytes) -> bytes:
    """Length-prefix a payload (the reference's framing, кластер.py:119)."""
    return struct.pack("<I", len(payload)) + payload


def unpack_message(buf: bytes) -> tuple[bytes, bytes]:
    """(payload, rest) from a length-prefixed buffer; raises if truncated."""
    if len(buf) < 4:
        raise ValueError("truncated frame: missing length prefix")
    (n,) = struct.unpack_from("<I", buf, 0)
    if len(buf) < 4 + n:
        raise ValueError(f"truncated frame: need {n} payload bytes, have {len(buf) - 4}")
    return bytes(buf[4 : 4 + n]), bytes(buf[4 + n :])
