"""ctypes loader/builder for the native runtime components (csrc/).

The reference reached native code through the mgzip wheel (кластер.py:51,62);
here the native components are part of the framework:

- ``libdwz.so`` (csrc/wire.cc): block-parallel deflate codec with a C ABI.
  ``load()`` returns a wrapper exposing ``compress``/``decompress`` with the
  exact signature wire.py expects; any failure returns None and wire.py
  stays on its pure-Python zlib path.
- ``libdwbatch.so`` (csrc/batch.cc): fused gather–cast–pack batch assembly
  for the ShardedLoader host input path.  ``load_batch()`` returns a
  :class:`NativeBatch` (or None), and data/loader.py falls back to the
  byte-identical numpy path — same discipline as the wire codec.

Each ``load*()`` finds a prebuilt ``.so`` (or builds it with g++ on first
use); failures are cached so a missing toolchain costs one probe, not one
per call.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "csrc")
_LIB = os.path.join(_CSRC, "libdwz.so")
_BATCH_LIB = os.path.join(_CSRC, "libdwbatch.so")
_MAX_THREADS = min(12, os.cpu_count() or 1)  # reference thread=12 (кластер.py:51)

_lock = threading.Lock()
_cached: Dict[str, object] = {}
_failed: Dict[str, bool] = {}


class NativeWire:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.dwz_compress.restype = ctypes.c_int
        lib.dwz_compress.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.dwz_decompress.restype = ctypes.c_int
        lib.dwz_decompress.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.dwz_free.restype = None
        lib.dwz_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]

    def _take(self, out, out_len) -> bytes:
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.dwz_free(out)

    def compress(self, data: bytes, level: int, block_size: int) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = self._lib.dwz_compress(
            data, len(data), level, block_size, _MAX_THREADS,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if rc != 0:
            raise RuntimeError(f"dwz_compress failed with code {rc}")
        return self._take(out, out_len)

    def decompress(self, data: bytes) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = self._lib.dwz_decompress(
            data, len(data), _MAX_THREADS, ctypes.byref(out), ctypes.byref(out_len)
        )
        if rc == -5:
            raise ValueError("bad wire magic; not a DWZ1 frame")
        if rc == -6:
            raise ValueError("truncated frame")
        if rc == -7:
            raise ValueError("trailing garbage in frame")
        if rc != 0:
            raise ValueError(f"corrupt frame (dwz_decompress code {rc})")
        return self._take(out, out_len)


def check_label_range(lo, hi) -> None:
    """The compact-cast label contract, shared verbatim by the numpy paths
    (data/loader.py) and the kernel's rc=-3 translation below: int8 labels
    with the -1 void sentinel.  One site owns the bounds and the message."""
    if lo < -1 or hi > 127:
        raise ValueError(
            f"compact=True needs labels in [-1, 127] for int8, "
            f"got range [{lo}, {hi}]"
        )


class NativeBatch:
    """Fused gather(+compact cast)+pack into caller-owned buffers
    (csrc/batch.cc).  One memory pass, tiles fanned over a thread pool;
    ctypes releases the GIL for the duration of the call."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.dwb_gather_pack.restype = ctypes.c_int
        lib.dwb_gather_pack.argtypes = [
            ctypes.c_void_p,  # images fp32 [n_src, img_elems]
            ctypes.c_void_p,  # labels int32 [n_src, lab_elems]
            ctypes.c_void_p,  # indices int64 [n_out]
            ctypes.c_size_t,  # n_out
            ctypes.c_size_t,  # n_src
            ctypes.c_size_t,  # img_elems
            ctypes.c_size_t,  # lab_elems
            ctypes.c_int,     # compact
            ctypes.c_void_p,  # img_out
            ctypes.c_void_p,  # lab_out
            ctypes.POINTER(ctypes.c_int32),  # lab_range[2]
            ctypes.c_int,     # max_threads
        ]

    def gather_pack(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        indices: np.ndarray,
        img_out: np.ndarray,
        lab_out: np.ndarray,
        compact: bool,
    ) -> None:
        """Gather ``images[indices]``/``labels[indices]`` into the
        preallocated outputs, casting bf16/int8 when ``compact``.  The
        caller (data/loader.py) validates dtypes/contiguity — this wrapper
        only asserts the invariants cheaply and translates error codes to
        the numpy path's exceptions."""
        n_out = len(indices)
        n_src = images.shape[0]
        img_elems = int(np.prod(images.shape[1:], dtype=np.int64))
        lab_elems = int(np.prod(labels.shape[1:], dtype=np.int64))
        # Hard raises, not asserts: these guard raw-pointer writes in C —
        # under python -O an assert vanishes and a size mismatch becomes
        # silent out-of-bounds memory corruption instead of an exception.
        if not (indices.dtype == np.int64 and indices.flags.c_contiguous):
            raise ValueError("indices must be a C-contiguous int64 array")
        if img_out.size != n_out * img_elems or lab_out.size != n_out * lab_elems:
            raise ValueError(
                f"destination sizes ({img_out.size}, {lab_out.size}) do not "
                f"match {n_out} tiles of ({img_elems}, {lab_elems}) elements"
            )
        lab_range = (ctypes.c_int32 * 2)()
        rc = self._lib.dwb_gather_pack(
            images.ctypes.data, labels.ctypes.data, indices.ctypes.data,
            n_out, n_src, img_elems, lab_elems, int(compact),
            img_out.ctypes.data, lab_out.ctypes.data, lab_range,
            _MAX_THREADS,
        )
        if rc == -3:
            check_label_range(lab_range[0], lab_range[1])
        if rc == -2:
            raise IndexError(
                f"gather index out of range for dataset of {n_src} tiles"
            )
        if rc != 0:
            raise RuntimeError(f"dwb_gather_pack failed with code {rc}")


def _build(target: str) -> bool:
    if not os.path.exists(os.path.join(_CSRC, "Makefile")):
        return False
    try:
        subprocess.run(
            ["make", "-s", target],
            cwd=_CSRC,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(os.path.join(_CSRC, target))
    except Exception:
        return False


def _load(path: str, wrapper, source: str, build: bool):
    """Shared load-or-build-once core; failures cached per library."""
    name = os.path.basename(path)
    with _lock:
        if name in _cached:
            return _cached[name]
        if _failed.get(name):
            return None
        if not os.path.exists(path):
            if not (
                build
                and os.path.exists(os.path.join(_CSRC, source))
                and _build(name)
            ):
                _failed[name] = True
                return None
        try:
            _cached[name] = wrapper(ctypes.CDLL(path))
        except (OSError, AttributeError):
            _failed[name] = True
            return None
        return _cached[name]


def load(build: bool = True) -> Optional[NativeWire]:
    """The loaded native wire codec, building it on first use; None on
    failure (wire.py stays on its pure-Python zlib path)."""
    return _load(_LIB, NativeWire, "wire.cc", build)


def load_batch(build: bool = True) -> Optional[NativeBatch]:
    """The loaded native batch-assembly kernel, building it on first use;
    None on failure (data/loader.py logs once and stays on numpy)."""
    return _load(_BATCH_LIB, NativeBatch, "batch.cc", build)
