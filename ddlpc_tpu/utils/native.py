"""ctypes loader/builder for the native wire codec (csrc/wire.cc).

The reference reached native code through the mgzip wheel (кластер.py:51,62);
here the native component is part of the framework: a C++ block-parallel
deflate codec with a C ABI.  ``load()`` finds a prebuilt ``libdwz.so`` (or
builds it with g++ on first use) and returns a thin wrapper exposing
``compress``/``decompress`` with the exact signature wire.py expects; any
failure returns None and wire.py stays on its pure-Python zlib path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "csrc")
_LIB = os.path.join(_CSRC, "libdwz.so")
_MAX_THREADS = min(12, os.cpu_count() or 1)  # reference thread=12 (кластер.py:51)

_lock = threading.Lock()
_cached: Optional["NativeWire"] = None
_failed = False


class NativeWire:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.dwz_compress.restype = ctypes.c_int
        lib.dwz_compress.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.dwz_decompress.restype = ctypes.c_int
        lib.dwz_decompress.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.dwz_free.restype = None
        lib.dwz_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]

    def _take(self, out, out_len) -> bytes:
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.dwz_free(out)

    def compress(self, data: bytes, level: int, block_size: int) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = self._lib.dwz_compress(
            data, len(data), level, block_size, _MAX_THREADS,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if rc != 0:
            raise RuntimeError(f"dwz_compress failed with code {rc}")
        return self._take(out, out_len)

    def decompress(self, data: bytes) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = self._lib.dwz_decompress(
            data, len(data), _MAX_THREADS, ctypes.byref(out), ctypes.byref(out_len)
        )
        if rc == -5:
            raise ValueError("bad wire magic; not a DWZ1 frame")
        if rc == -6:
            raise ValueError("truncated frame")
        if rc == -7:
            raise ValueError("trailing garbage in frame")
        if rc != 0:
            raise ValueError(f"corrupt frame (dwz_decompress code {rc})")
        return self._take(out, out_len)


def _build() -> bool:
    if not os.path.exists(os.path.join(_CSRC, "wire.cc")):
        return False
    try:
        subprocess.run(
            ["make", "-s", "libdwz.so"],
            cwd=_CSRC,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB)
    except Exception:
        return False


def load(build: bool = True) -> Optional[NativeWire]:
    """The loaded native codec, building it on first use; None on failure."""
    global _cached, _failed
    with _lock:
        if _cached is not None:
            return _cached
        if _failed:
            return None
        if not os.path.exists(_LIB) and not (build and _build()):
            _failed = True
            return None
        try:
            _cached = NativeWire(ctypes.CDLL(_LIB))
        except OSError:
            _failed = True
            return None
        return _cached
