"""Utilities: wire codec (framed, block-parallel compression), timing."""

from ddlpc_tpu.utils.wire import (  # noqa: F401
    compress,
    decompress,
    pack_message,
    unpack_message,
)
