"""Bounded runtime exercises for the lock-order detector.

``run_smoke`` drives the repo's instrumented concurrency hot spots — the
MicroBatcher, StageTimer, Tracer, HealthMonitor, CircuitBreaker and (when
jax is importable) the loader's ``_HostRing`` — under real thread
contention for a fraction of a second, then returns the recorded
acquisition graph and guard violations.  ``scripts/ddlpc_check.py`` runs
it on every invocation and fails on any cycle or guarded-by violation;
the same classes also run instrumented whenever the tier-1 threaded tests
execute with ``DDLPC_LOCKCHECK=1``.

``inversion_demo`` is the committed NEGATIVE fixture: two locks taken in
opposite orders on two threads — the analyzer must fail on it
(``tests/test_analysis.py`` pins that it does).
"""

from __future__ import annotations

import threading
from typing import Optional

from ddlpc_tpu.analysis import lockcheck


def _threads(n: int, fn) -> None:
    ts = [threading.Thread(target=fn, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def run_smoke(workdir: Optional[str] = None) -> dict:
    """Exercise the instrumented classes; returns ``lockcheck.report()``.

    Must be called with lockcheck enabled (the CLI does).  Each arm is a
    few hundred operations — enough to cross every lock pair the classes
    can produce, cheap enough to run on every ``ddlpc_check``.
    """
    import os
    import tempfile

    from ddlpc_tpu.obs.health import Alert, HealthMonitor
    from ddlpc_tpu.obs.tracing import Tracer
    from ddlpc_tpu.serve.batching import MicroBatcher
    from ddlpc_tpu.serve.router import CircuitBreaker

    report: dict = {"arms": []}

    # MicroBatcher: concurrent submit/shed/drain against a live worker.
    mb = MicroBatcher(
        forward=lambda xs: [x * 2 for x in xs],
        max_batch=4,
        max_wait_ms=1.0,
        queue_limit=64,
    )

    def submit(i: int) -> None:
        for k in range(20):
            try:
                mb.submit(k).result(timeout=5)
            except Exception:
                pass
            mb.queue_depth  # noqa: B018  — cross-thread read path

    _threads(4, submit)
    mb.close(drain=True)
    report["arms"].append("MicroBatcher")

    # Tracer: spans from several threads + cross-thread add_span + flush.
    with tempfile.TemporaryDirectory(dir=workdir) as td:
        tr = Tracer(
            enabled=True,
            jsonl_path=os.path.join(td, "spans.jsonl"),
            chrome_path=os.path.join(td, "trace.json"),
        )

        def trace(i: int) -> None:
            for k in range(15):
                with tr.span(f"phase{i}", k=k):
                    pass
                tr.add_span("xthread", tr.now(), tr.now())

        _threads(4, trace)
        tr.flush()
        tr.close()
    report["arms"].append("Tracer")

    # HealthMonitor: emit storm vs /healthz-style snapshot reads.
    hm = HealthMonitor()

    def health(i: int) -> None:
        for k in range(20):
            hm.emit(
                Alert(
                    alert="step_time_regression",
                    severity="warn",
                    message="lockcheck smoke",
                    value=float(k),
                    threshold=1.0,
                )
            )
            hm.alerts

    _threads(3, health)
    report["arms"].append("HealthMonitor")

    # CircuitBreaker: outcome storm across the latch transitions.
    br = CircuitBreaker(window=8, min_samples=4, cooldown_s=0.0)

    def breaker(i: int) -> None:
        for k in range(30):
            if br.acquire():
                br.record(k % 3 != 0)
            br.available()
            if k % 7 == 0:
                br.release()

    _threads(4, breaker)
    report["arms"].append("CircuitBreaker")

    # StageTimer and _HostRing live in jax-tier modules — exercise them
    # when the import works, note the skip when it doesn't (the analyzer
    # itself must run on a stdlib-only install).
    try:
        from ddlpc_tpu.data.loader import _HostRing, _Slot
        from ddlpc_tpu.train.observability import StageTimer
    except Exception as e:  # pragma: no cover - jax-less environment
        report["jax_arms_skipped"] = f"{type(e).__name__}: {e}"
    else:
        st = StageTimer()

        def stages(i: int) -> None:
            for _ in range(25):
                with st.stage(f"s{i % 3}"):
                    pass
                st.summary()
                st.means()

        _threads(4, stages)
        st.reset()
        report["arms"].append("StageTimer")

        ring = _HostRing(2, lambda reuse_scratch_from=None: _Slot(0, 0))

        def churn(i: int) -> None:
            for k in range(25):
                slot = ring.acquire()
                ring.release(slot, retire=(k % 5 == 0))

        _threads(4, churn)
        report["arms"].append("_HostRing")

    report.update(lockcheck.report())
    return report


def inversion_demo() -> dict:
    """Deliberate lock-order inversion: A→B on one thread, B→A on another
    (sequenced so the demo itself cannot deadlock).  The analyzer must
    report a cycle."""
    a = lockcheck.lock("demo.A")
    b = lockcheck.lock("demo.B")
    done_ab = threading.Event()

    def t_ab() -> None:
        with a:
            with b:
                pass
        done_ab.set()

    def t_ba() -> None:
        done_ab.wait(5)
        with b:
            with a:
                pass

    t1 = threading.Thread(target=t_ab)
    t2 = threading.Thread(target=t_ba)
    t1.start(), t2.start()
    t1.join(), t2.join()
    return lockcheck.report()
