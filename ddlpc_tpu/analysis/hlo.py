"""Walkers over compiled XLA programs: optimized-HLO text and jaxprs.

The compiled-program auditor (``analysis/program.py``, docs/ANALYSIS.md
"Program-level contracts") needs to *read* what XLA actually emitted —
which collectives run, what dtype feeds them, whether the
``optimization_barrier`` fences survived, which buffers were
input/output-aliased — without depending on XLA protobuf bindings.  This
module owns the two read paths:

- **HLO text** (:func:`parse_hlo_module`) — ``jit(f).lower(...).compile()
  .as_text()`` is stable, line-oriented HLO: one instruction per line,
  shapes spelled ``f32[64,33]{1,0}``, per-op ``metadata={...
  source_file=... source_line=N}`` tracing each op back to the Python
  that built it, and the module header carrying ``input_output_alias``
  (the donation ground truth) and ``entry_computation_layout``.  The
  parser extracts exactly what the auditor consumes — opcodes, result/
  operand shapes with byte sizes, source attribution, aliasing — and
  nothing else, so it does not pretend to be a full HLO grammar.

- **jaxpr** (:func:`jaxpr_collectives`, :func:`jaxpr_fence_count`) — the
  pre-lowering census for the ``--fast`` tier-1 mode: collective
  primitives and barrier equations counted straight off the traced
  program (``obs/flops.iter_eqns`` recursion, so scan/remat/shard_map
  bodies are included), no XLA compile paid.

Stdlib tier (analysis/tiers.py): pure text/structure walking; the jaxpr
helpers receive already-traced jaxpr objects and only touch their public
``eqns``/``avals`` attributes, so importing this module never pays jax.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ddlpc_tpu.obs.flops import iter_eqns

# Bits per element for the HLO primitive types the repo's programs emit.
# (s4/u4 exist upstream but no program here produces them; unknown dtypes
# fail loudly in shape_bytes rather than silently counting zero.)
DTYPE_BITS: Dict[str, int] = {
    "pred": 8,
    "s8": 8, "u8": 8,
    "s16": 16, "u16": 16, "f16": 16, "bf16": 16,
    "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64,
    "c64": 64, "c128": 128,
}

# HLO opcodes that move bytes between replicas.  Async forms (``-start``)
# are normalized to the base opcode; their ``-done`` halves carry no
# payload and are skipped.
COLLECTIVE_OPCODES = frozenset(
    {
        "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
        "collective-permute", "collective-broadcast",
    }
)
_ASYNC_SUFFIX = "-start"
_ASYNC_DONE = frozenset(
    c + "-done" for c in COLLECTIVE_OPCODES
) | frozenset({"all-reduce-done", "collective-permute-done"})

# jaxpr collective primitive -> HLO opcode family.  ``pmean`` is not a
# primitive (psum + divide); ``pmax``/``pmin`` lower to all-reduce with a
# max/min computation.
JAXPR_COLLECTIVES: Dict[str, str] = {
    "psum": "all-reduce",
    "pmax": "all-reduce",
    "pmin": "all-reduce",
    "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter",  # lax.psum_scatter's primitive
    "ppermute": "collective-permute",
    "pbroadcast": "collective-broadcast",
    "all_to_all": "all-to-all",
}

FENCE_PRIMITIVE = "optimization_barrier"
FENCE_OPCODE = "opt-barrier"


# --------------------------------------------------------------------------
# shapes
# --------------------------------------------------------------------------


_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]+\d*)\[(?P<dims>[\d,]*)\]")


@dataclass(frozen=True)
class Shape:
    dtype: str
    dims: Tuple[int, ...]

    @property
    def elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def bytes(self) -> int:
        return shape_bytes(self.dtype, self.dims)


def shape_bytes(dtype: str, dims: Tuple[int, ...]) -> int:
    """Payload bytes of one dense array shape."""
    if dtype in ("token", "opaque"):
        return 0
    bits = DTYPE_BITS.get(dtype)
    if bits is None:
        raise ValueError(f"unknown HLO element type {dtype!r}")
    n = 1
    for d in dims:
        n *= d
    return (n * bits) // 8


def parse_shapes(text: str) -> List[Shape]:
    """Every array shape spelled in ``text`` (tuple shapes contribute one
    entry per element)."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        dims = tuple(int(d) for d in m.group("dims").split(",") if d)
        out.append(Shape(m.group("dtype"), dims))
    return out


# --------------------------------------------------------------------------
# HLO instruction parsing
# --------------------------------------------------------------------------


# Result shapes are either one array (`f32[64,33]{1,0}`) or a tuple
# (`(f32[6]{0}, /*index=5*/f32[16]{0}, ...)`) — tuple bodies never nest
# parens but DO carry `/*index=N*/` comments, so match on non-paren
# content, not on "no '='".
_INSN_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^()]*\)|[a-z]+\d*\[[\d,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<opcode>[\w\-]+)\("
)
_META_RE = re.compile(
    r'metadata=\{[^}]*?op_name="(?P<op_name>[^"]*)"'
    r'(?:[^}]*?source_file="(?P<source_file>[^"]*)")?'
    r"(?:[^}]*?source_line=(?P<source_line>\d+))?"
)
_ALIAS_ENTRY_RE = re.compile(
    r"\{(?P<out>[\d,\s]*)\}:\s*\((?P<param>\d+),\s*\{(?P<pidx>[\d,\s]*)\},"
    r"\s*(?P<kind>may-alias|must-alias)\)"
)


def _brace_block(text: str, marker: str) -> str:
    """The ``{...}`` block (content only) following ``marker=``, matched by
    brace depth — header attributes nest braces (shape layouts, alias
    entries), so regex-to-first-close is wrong."""
    start = text.find(marker + "={")
    if start < 0:
        return ""
    i = text.index("{", start)
    depth = 0
    for j in range(i, len(text)):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                return text[i + 1 : j]
    return text[i + 1 :]


@dataclass
class HloOp:
    """One HLO instruction: opcode + result/operand shapes + provenance."""

    name: str
    opcode: str
    results: List[Shape]
    operands: List[Shape] = field(default_factory=list)
    op_name: str = ""
    source_file: str = ""
    source_line: int = 0

    @property
    def result_bytes(self) -> int:
        return sum(s.bytes for s in self.results)

    @property
    def operand_bytes(self) -> int:
        return sum(s.bytes for s in self.operands)


def _operand_section(line: str, open_idx: int) -> str:
    """The text between the opcode's ``(`` and its matching ``)``."""
    depth = 0
    for i in range(open_idx, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return line[open_idx + 1 : i]
    return line[open_idx + 1 :]


def parse_hlo_ops(text: str) -> List[HloOp]:
    """Every instruction in an HLO module dump, in order.

    Operand shapes come from the operand list between the opcode's
    parentheses (attribute text after the closing paren — ``to_apply``,
    ``metadata``, constant literals — never contributes shapes).
    """
    ops: List[HloOp] = []
    for line in text.splitlines():
        m = _INSN_RE.match(line)
        if m is None:
            continue
        opcode = m.group("opcode")
        results = parse_shapes(m.group("shape"))
        open_idx = line.index("(", m.end() - 1)
        operands = parse_shapes(_operand_section(line, open_idx))
        op = HloOp(
            name=m.group("name"), opcode=opcode,
            results=results, operands=operands,
        )
        meta = _META_RE.search(line)
        if meta is not None:
            op.op_name = meta.group("op_name") or ""
            op.source_file = meta.group("source_file") or ""
            op.source_line = int(meta.group("source_line") or 0)
        ops.append(op)
    return ops


@dataclass
class HloModule:
    """Parsed view of one optimized-HLO text dump."""

    ops: List[HloOp]
    # output-tuple index -> entry parameter number (the donation map)
    aliases: Dict[Tuple[int, ...], int]
    entry_params: List[Shape]
    entry_outputs: List[Shape]

    def count(self, opcode: str) -> int:
        return sum(1 for op in self.ops if op.opcode == opcode)

    @property
    def fence_count(self) -> int:
        return self.count(FENCE_OPCODE)

    @property
    def aliased_params(self) -> List[int]:
        return sorted({p for p in self.aliases.values()})


def _parse_entry_layout(text: str) -> Tuple[List[Shape], List[Shape]]:
    body = _brace_block(text, "entry_computation_layout")
    if not body:
        return [], []
    arrow = body.find("->")
    if arrow < 0:
        return parse_shapes(body), []
    return parse_shapes(body[:arrow]), parse_shapes(body[arrow + 2 :])


def parse_hlo_module(text: str) -> HloModule:
    """Parse a ``compiled.as_text()`` dump: instructions + header facts."""
    aliases: Dict[Tuple[int, ...], int] = {}
    header = text.splitlines()[0] if text else ""
    for entry in _ALIAS_ENTRY_RE.finditer(
        _brace_block(header, "input_output_alias")
    ):
        out_idx = tuple(
            int(x) for x in entry.group("out").replace(" ", "").split(",")
            if x
        )
        aliases[out_idx] = int(entry.group("param"))
    params, outputs = _parse_entry_layout(header)
    return HloModule(
        ops=parse_hlo_ops(text),
        aliases=aliases,
        entry_params=params,
        entry_outputs=outputs,
    )


# --------------------------------------------------------------------------
# census rows (shared shape between the HLO and jaxpr levels)
# --------------------------------------------------------------------------


@dataclass
class CensusRow:
    """Aggregated collectives of one (kind, dtype, group) signature.

    ``bytes`` is the per-replica payload under the convention the byte
    accounting in ``obs/comm.py`` uses: all-reduce and reduce-scatter
    count the bytes a replica CONTRIBUTES (operand bytes), all-gather
    counts the bytes it RECEIVES (result bytes — the full published
    tensor, matching ``comm_plan``'s all_gather row), collective-permute
    counts the bytes each hop sends (operand bytes).
    """

    kind: str
    dtype: str
    group: str = "wire"
    count: int = 0
    elements: int = 0
    bytes: int = 0

    def key(self) -> Tuple[str, str, str]:
        return (self.kind, self.dtype, self.group)

    def to_dict(self) -> Dict[str, object]:
        return {
            "kind": self.kind, "dtype": self.dtype, "group": self.group,
            "count": self.count, "elements": self.elements,
            "bytes": self.bytes,
        }


def _payload_shapes(kind: str, results: List[Shape], operands: List[Shape]):
    if kind == "all-gather":
        return results
    return operands


def hlo_collective_census(
    ops: List[HloOp], classify=None
) -> List[CensusRow]:
    """Aggregate the module's collectives into :class:`CensusRow` rows.

    ``classify(op) -> group name`` buckets each collective (the auditor
    separates gradient-wire collectives from auxiliary ones by source
    attribution); default: everything in one ``"all"`` group.
    """
    rows: Dict[Tuple[str, str, str], CensusRow] = {}
    for op in ops:
        kind = op.opcode
        if kind.endswith(_ASYNC_SUFFIX):
            kind = kind[: -len(_ASYNC_SUFFIX)]
        if kind not in COLLECTIVE_OPCODES or op.opcode in _ASYNC_DONE:
            continue
        payload = _payload_shapes(kind, op.results, op.operands)
        if not payload:
            continue
        group = classify(op) if classify is not None else "all"
        for sh in payload:
            row = rows.setdefault(
                (kind, sh.dtype, group), CensusRow(kind, sh.dtype, group)
            )
            row.elements += sh.elements
            row.bytes += sh.bytes
        # The instruction counts once, attributed to its first payload
        # dtype (multi-dtype tuple collectives split bytes per dtype row).
        rows[(kind, payload[0].dtype, group)].count += 1
    return sorted(rows.values(), key=CensusRow.key)


# --------------------------------------------------------------------------
# jaxpr level (fast mode — no compile)
# --------------------------------------------------------------------------


_JAX_DTYPE_TO_HLO = {
    "float32": "f32", "float16": "f16", "bfloat16": "bf16",
    "float64": "f64",
    "int8": "s8", "uint8": "u8", "int16": "s16", "uint16": "u16",
    "int32": "s32", "uint32": "u32", "int64": "s64", "uint64": "u64",
    "bool": "pred",
}


def hlo_dtype_name(dtype) -> str:
    """HLO spelling of a numpy/jax dtype (so both census levels speak the
    same dtype vocabulary)."""
    name = getattr(dtype, "name", str(dtype))
    return _JAX_DTYPE_TO_HLO.get(name, name)


def jaxpr_collectives(jaxpr) -> List[CensusRow]:
    """Collective census of a (closed or raw) jaxpr, recursing into
    sub-jaxprs.  One equation counts once, with payload bytes summed over
    its array operands (all-gather: its outputs, matching the HLO
    convention)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    rows: Dict[Tuple[str, str, str], CensusRow] = {}
    for eqn in iter_eqns(inner):
        kind = JAXPR_COLLECTIVES.get(eqn.primitive.name)
        if kind is None:
            continue
        vs = eqn.outvars if kind == "all-gather" else eqn.invars
        avals = [
            v.aval for v in vs if getattr(v, "aval", None) is not None
            and hasattr(v.aval, "shape")
        ]
        for aval in avals:
            dtype = hlo_dtype_name(aval.dtype)
            row = rows.setdefault(
                (kind, dtype, "all"), CensusRow(kind, dtype, "all")
            )
            n = 1
            for d in aval.shape:
                n *= int(d)
            row.elements += n
            row.bytes += shape_bytes(dtype, tuple(int(d) for d in aval.shape))
        if avals:
            first = hlo_dtype_name(avals[0].dtype)
            rows[(kind, first, "all")].count += 1
    return sorted(rows.values(), key=CensusRow.key)


def jaxpr_fence_count(jaxpr) -> int:
    """Number of ``optimization_barrier`` equations (fences) in a jaxpr,
    sub-jaxprs included."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    return sum(
        1 for eqn in iter_eqns(inner)
        if eqn.primitive.name == FENCE_PRIMITIVE
    )


def census_to_dicts(rows: List[CensusRow]) -> List[Dict[str, object]]:
    return [r.to_dict() for r in rows]


def census_diff(
    expected: List[Dict[str, object]], actual: List[Dict[str, object]]
) -> List[str]:
    """Human-readable drift between two census tables (empty = identical).

    Keys on (kind, dtype, group); any field difference — a new collective,
    a changed dtype, different counts or bytes — is one message naming the
    op signature, so a failing gate says WHAT changed, not just "drift".
    """

    def index(rows):
        return {
            (r["kind"], r["dtype"], r.get("group", "all")): r for r in rows
        }

    exp, act = index(expected), index(actual)
    out: List[str] = []
    for key in sorted(set(exp) | set(act)):
        kind, dtype, group = key
        sig = f"{kind}[{dtype}] ({group})"
        if key not in act:
            out.append(f"collective disappeared: {sig} "
                       f"(baseline count={exp[key]['count']})")
        elif key not in exp:
            out.append(
                f"new collective: {sig} count={act[key]['count']} "
                f"bytes={act[key]['bytes']}"
            )
        else:
            for fld in ("count", "elements", "bytes"):
                if exp[key][fld] != act[key][fld]:
                    out.append(
                        f"{sig} {fld} changed: baseline {exp[key][fld]} "
                        f"-> {act[key][fld]}"
                    )
    return out


def max_operand_itemsize(row_dtype: str) -> int:
    """Bytes per element of an HLO dtype (dtype-flow comparisons)."""
    return DTYPE_BITS[row_dtype] // 8
