"""AST rule engine: shared visitor, suppressions, analysis orchestration.

One parse per file; every rule sees every node through a single walk
(rules implement ``visit_<NodeType>`` methods, cross-file rules aggregate
in ``finalize``).  Suppression is per line::

    f.write(json.dumps(rec) + "\\n")  # ddlpc-check: disable=jsonl-stamp pass-through of already-stamped records

A suppression comment without a written reason is itself a violation
(``bad-suppression``) — the whole point is that every exemption carries
its argument in the diff.  Suppressed violations are counted and reported
in the summary, never silently dropped.
"""

from __future__ import annotations

import ast
import io
import os
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ddlpc_tpu.analysis import tiers as tiers_mod

SUPPRESS_MARK = "ddlpc-check:"


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclass
class FileContext:
    """Everything a rule may need about the file being visited."""

    path: str  # absolute
    rel: str  # relative to the analysis root (stable in reports)
    root: str
    tree: ast.Module = None
    src: str = ""
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None


class Rule:
    """Base: one invariant, one id, one doc line (docs/ANALYSIS.md)."""

    id: str = ""
    doc: str = ""

    def begin_file(self, ctx: FileContext) -> None:  # pragma: no cover
        pass

    def end_file(self, ctx: FileContext) -> None:  # pragma: no cover
        pass

    def finalize(self, root: str) -> List[Violation]:
        return []


def _parse_suppressions(
    src: str, path: str
) -> Tuple[Dict[int, Dict[str, str]], List[Violation]]:
    """line -> {rule_id: reason}; malformed suppressions come back as
    violations.  A comment on its own line also covers the next line."""
    per_line: Dict[int, Dict[str, str]] = {}
    bad: List[Violation] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [
            (t.start[0], t.string, t.line)
            for t in tokens
            if t.type == tokenize.COMMENT and SUPPRESS_MARK in t.string
        ]
    except (tokenize.TokenError, SyntaxError):
        # IndentationError (a SyntaxError) also escapes tokenize; fall
        # through with no suppressions — ast.parse reports the file as a
        # syntax-error violation on the normal path.
        return per_line, bad
    for lineno, text, logical in comments:
        body = text.split(SUPPRESS_MARK, 1)[1].strip()
        if not body.startswith("disable="):
            bad.append(
                Violation(
                    "bad-suppression", path, lineno,
                    f"unrecognized ddlpc-check directive {text.strip()!r} "
                    f"(expected '# ddlpc-check: disable=RULE reason')",
                )
            )
            continue
        rest = body[len("disable="):]
        parts = rest.split(None, 1)
        rules = [r for r in parts[0].split(",") if r]
        reason = parts[1].strip() if len(parts) > 1 else ""
        if not reason:
            bad.append(
                Violation(
                    "bad-suppression", path, lineno,
                    "suppression without a reason — write WHY the rule "
                    "does not apply here",
                )
            )
            continue
        targets = [lineno]
        if logical.strip().startswith("#"):
            targets.append(lineno + 1)  # standalone comment covers next line
        for ln in targets:
            slot = per_line.setdefault(ln, {})
            for r in rules:
                slot[r] = reason
    return per_line, bad


def collect_files(root: str) -> List[str]:
    """The analysis surface: ddlpc_tpu/ (recursive) + scripts/ (flat)."""
    out: List[str] = []
    pkg = os.path.join(root, "ddlpc_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(
            os.path.join(dirpath, f)
            for f in filenames
            if f.endswith(".py")
        )
    scripts = os.path.join(root, "scripts")
    if os.path.isdir(scripts):
        out.extend(
            os.path.join(scripts, f)
            for f in sorted(os.listdir(scripts))
            if f.endswith(".py")
        )
    return sorted(out)


@dataclass
class AnalysisResult:
    violations: List[Violation]
    files_scanned: int
    duration_s: float
    rules_run: List[str]

    @property
    def unsuppressed(self) -> List[Violation]:
        return [v for v in self.violations if not v.suppressed]

    @property
    def suppressed(self) -> List[Violation]:
        return [v for v in self.violations if v.suppressed]


class _Dispatch(ast.NodeVisitor):
    def __init__(self, rules, ctx: FileContext):
        self.handlers: Dict[str, list] = {}
        for r in rules:
            for attr in dir(r):
                if attr.startswith("visit_") and attr != "visit_":
                    self.handlers.setdefault(attr[6:], []).append(
                        getattr(r, attr)
                    )
        self.ctx = ctx

    def generic_visit(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self.ctx.parents[child] = node
        for h in self.handlers.get(type(node).__name__, ()):
            h(node, self.ctx)
        super().generic_visit(node)


def run_analysis(
    root: str,
    rule_ids: Optional[Set[str]] = None,
    include_tiers: bool = True,
) -> AnalysisResult:
    """Run the import-graph checker + AST rules over ``root``.

    ``rule_ids`` filters to a subset (tier rules included only when named
    or when the filter is absent).  Suppressions are applied here so every
    caller — CLI, tests — sees identical semantics.
    """
    from ddlpc_tpu.analysis.rules import make_rules

    t0 = time.perf_counter()
    violations: List[Violation] = []
    rules = [
        r
        for r in make_rules()
        if rule_ids is None or r.id in rule_ids
    ]
    rules_run = [r.id for r in rules]

    pkg_dir = os.path.join(root, "ddlpc_tpu")
    tier_wanted = rule_ids is None or bool(
        {"import-tier", "tier-undeclared"} & rule_ids
    )
    if include_tiers and tier_wanted and os.path.isdir(pkg_dir):
        for rule_id, path, line, msg in tiers_mod.check_tiers(pkg_dir):
            violations.append(Violation(rule_id, path, line, msg))
        rules_run = ["import-tier", "tier-undeclared"] + rules_run

    files = collect_files(root)
    suppress_maps: Dict[str, Dict[int, Dict[str, str]]] = {}
    for path in files:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, root)
        sup, bad = _parse_suppressions(src, path)
        suppress_maps[path] = sup
        violations.extend(bad)
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            violations.append(
                Violation(
                    "syntax-error", path, e.lineno or 1,
                    f"file does not parse: {e.msg}",
                )
            )
            continue
        ctx = FileContext(path=path, rel=rel, root=root, tree=tree, src=src)
        for r in rules:
            r.begin_file(ctx)
        _Dispatch(rules, ctx).visit(tree)
        for r in rules:
            r.end_file(ctx)
    for r in rules:
        violations.extend(r.finalize(root))

    # apply suppressions (tier violations can be suppressed too — the
    # comment lives on the flagged import line)
    for v in violations:
        sup = suppress_maps.get(v.path, {})
        reason = sup.get(v.line, {}).get(v.rule)
        if reason is not None:
            v.suppressed = True
            v.reason = reason
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return AnalysisResult(
        violations=violations,
        files_scanned=len(files),
        duration_s=time.perf_counter() - t0,
        rules_run=rules_run,
    )
