"""Project invariant analyzer (``scripts/ddlpc_check.py``, docs/ANALYSIS.md).

Four arms, one command:

- :mod:`tiers` — import-graph checker: every ``ddlpc_tpu`` module declares
  an import-time tier (``stdlib`` / ``host`` / ``jax``) in one registry,
  and the checker transitively proves the declaration — the supervisor and
  routing tiers stay jax-free so a fleet restart never pays an XLA import;
- :mod:`rules` — AST rules over ``ddlpc_tpu/`` + ``scripts/`` (one class
  per rule, shared visitor, ``# ddlpc-check: disable=RULE reason``
  suppressions): schema-stamped JSONL emits, metric-name/docs drift,
  tmp+fsync+rename report writes, host calls inside jitted functions,
  fenced codec invocations in ``parallel/``;
- :mod:`lockcheck` — instrumented ``Lock``/``RLock``/``Condition``
  recording the cross-thread lock-acquisition graph (cycle = lock-order
  inversion) and enforcing ``# guarded-by:`` attribute annotations at
  runtime; near-zero cost when disabled;
- sanitizer wiring lives in ``csrc/Makefile`` (``make -C csrc sanitize``)
  with a build-or-skip canary in ``tests/test_analysis.py``.

This package (minus :mod:`lock_fixtures`, which imports the serve tier to
exercise it) is pure stdlib, so the analyzer itself runs without jax.
"""

from __future__ import annotations
