"""Module tier registry + transitive import-graph checker.

Every module under ``ddlpc_tpu`` declares the *import-time* dependency
surface it is allowed — in THIS file, so adding a module forces an
explicit tier decision in review:

- ``stdlib`` — stdlib + same-or-lower-tier ``ddlpc_tpu`` modules only.
  The telemetry substrate and the resilience protocol live here: they are
  importable in any thread, any process, with nothing installed.
- ``host`` — third-party host libraries (numpy, PIL, ...) allowed;
  ``jax``/``jaxlib``/``flax``/``optax`` forbidden, TRANSITIVELY.  This is
  the property that makes PR 9's fleet restart fast: the supervisor and
  routing tiers never pay an XLA import, so a replica relaunch is
  milliseconds of Python, not seconds of jax init.
- ``jax`` — the accelerator tier; anything goes.

The checker (:func:`check_tiers`) parses module-level imports with
``ast`` (imports inside functions are deliberate lazy escapes and do not
count — the runtime meta-path test in ``tests/test_analysis.py`` pins
that they stay lazy), adds the implicit parent-package edges (importing
``a.b.c`` executes ``a/__init__`` and ``a/b/__init__`` first), and walks
the closure.  A ``host``-tier module that can reach an ``import jax``
fails with the full chain, file:line of the offending import included.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, Iterable, List, Optional, Tuple

STDLIB, HOST, JAX = "stdlib", "host", "jax"
_RANK = {STDLIB: 0, HOST: 1, JAX: 2}

# Import roots forbidden below the jax tier.  ``jnp`` etc. are attributes
# of jax, so the root covers them.
JAX_ROOTS = frozenset({"jax", "jaxlib", "flax", "optax"})

# The one registry.  New modules must be added here explicitly — an
# undeclared module is a violation (rule ``tier-undeclared``), as is a
# declaration for a module that no longer exists.
MODULE_TIERS: Dict[str, str] = {
    "ddlpc_tpu": STDLIB,
    "ddlpc_tpu.config": STDLIB,
    # obs: everything except the profiling hooks is pure stdlib by
    # charter (obs/__init__.py docstring).
    "ddlpc_tpu.obs": STDLIB,
    "ddlpc_tpu.obs.schema": STDLIB,
    "ddlpc_tpu.obs.registry": STDLIB,
    "ddlpc_tpu.obs.tracing": STDLIB,
    "ddlpc_tpu.obs.health": STDLIB,
    "ddlpc_tpu.obs.http": STDLIB,
    "ddlpc_tpu.obs.flops": STDLIB,
    "ddlpc_tpu.obs.comm": STDLIB,
    "ddlpc_tpu.obs.hbm": STDLIB,
    "ddlpc_tpu.obs.profiling": STDLIB,  # jax reached lazily, per capture
    "ddlpc_tpu.obs.xplane": STDLIB,  # TF proto import is optional/lazy
    # fleet observability (ISSUE 14): the trace merger and the telemetry
    # aggregator run in router/CI processes — provably jax-free, like the
    # routing tier they serve.
    "ddlpc_tpu.obs.merge": STDLIB,
    "ddlpc_tpu.obs.aggregate": STDLIB,
    # lineage (ISSUE 17): checkpoint provenance records.  Stdlib by
    # charter — the jax-free router tier reads checkpoint sidecars
    # through it for the model-age gauge.
    "ddlpc_tpu.obs.lineage": STDLIB,
    # resilience: the supervisor must restart a crashed trainer without
    # importing what crashed it.
    "ddlpc_tpu.resilience": STDLIB,
    "ddlpc_tpu.resilience.protocol": STDLIB,
    "ddlpc_tpu.resilience.supervisor": STDLIB,
    "ddlpc_tpu.resilience.chaos": STDLIB,
    # analysis: the analyzer itself runs without jax.
    "ddlpc_tpu.analysis": STDLIB,
    "ddlpc_tpu.analysis.core": STDLIB,
    "ddlpc_tpu.analysis.rules": STDLIB,
    "ddlpc_tpu.analysis.tiers": STDLIB,
    "ddlpc_tpu.analysis.lockcheck": STDLIB,
    "ddlpc_tpu.analysis.lock_fixtures": HOST,  # exercises the serve tier
    # the HLO/jaxpr walkers are pure text/structure (jaxpr objects come
    # in as arguments); the program auditor builds and lowers the real
    # step programs, so it owns the full accelerator stack (its jax
    # imports stay function-local so the baseline validators import
    # cheaply from perf_gate --smoke).
    "ddlpc_tpu.analysis.hlo": STDLIB,
    "ddlpc_tpu.analysis.program": JAX,
    # serve: the routing/fleet tier is jax-free (numpy allowed — the
    # engine's host-side tiling math); engine compiles lazily.
    "ddlpc_tpu.serve": HOST,
    # batching's own code is stdlib, but importing it executes
    # serve/__init__ (numpy via the engine) — tier describes the runtime
    # import closure, parent packages included.
    "ddlpc_tpu.serve.batching": HOST,
    "ddlpc_tpu.serve.cbatch": HOST,
    "ddlpc_tpu.serve.metrics": HOST,
    "ddlpc_tpu.serve.engine": HOST,
    # quantized's own imports are lazy (jax at quantization time, like
    # obs/profiling) so the engine can import it without paying jax;
    # router/fleet stay provably jax-free either way.
    "ddlpc_tpu.serve.quantized": HOST,
    "ddlpc_tpu.serve.server": HOST,
    "ddlpc_tpu.serve.router": HOST,
    "ddlpc_tpu.serve.fleet": HOST,
    # elastic-fleet control plane (ISSUE 16): both are stdlib-only code,
    # HOST for the same parent-package reason as batching — proving the
    # autoscaler/cache never pay a jax import is the point of the tier.
    "ddlpc_tpu.serve.autoscale": HOST,
    "ddlpc_tpu.serve.cache": HOST,
    # utils: wire/fsio are stdlib; native needs numpy; compat IS the jax
    # shim layer.
    "ddlpc_tpu.utils": STDLIB,
    "ddlpc_tpu.utils.wire": STDLIB,
    "ddlpc_tpu.utils.fsio": STDLIB,
    "ddlpc_tpu.utils.native": HOST,
    "ddlpc_tpu.utils.compat": JAX,
    "ddlpc_tpu.utils.backend_probe": JAX,
    # the accelerator tier
    "ddlpc_tpu.data": JAX,
    "ddlpc_tpu.data.datasets": JAX,
    "ddlpc_tpu.data.loader": JAX,
    "ddlpc_tpu.models": JAX,
    "ddlpc_tpu.models.layers": JAX,
    "ddlpc_tpu.models.unet": JAX,
    "ddlpc_tpu.models.unetpp": JAX,
    "ddlpc_tpu.models.deeplabv3p": JAX,
    "ddlpc_tpu.ops": JAX,
    "ddlpc_tpu.ops.losses": JAX,
    "ddlpc_tpu.ops.metrics": JAX,
    "ddlpc_tpu.ops.quantize": JAX,
    "ddlpc_tpu.ops.pallas_quantize": JAX,
    "ddlpc_tpu.parallel": JAX,
    "ddlpc_tpu.parallel.mesh": JAX,
    "ddlpc_tpu.parallel.halo": JAX,
    # jax-free by construction (obs/comm and tooling compute bucket
    # assignments without the accelerator stack), but the implicit
    # parent-package edge pins it to the parallel package's tier.
    "ddlpc_tpu.parallel.bucketing": JAX,
    "ddlpc_tpu.parallel.grad_sync": JAX,
    "ddlpc_tpu.parallel.compressed_allreduce": JAX,
    "ddlpc_tpu.parallel.partition": JAX,
    "ddlpc_tpu.parallel.pipeline": JAX,
    "ddlpc_tpu.parallel.shard_update": JAX,
    "ddlpc_tpu.parallel.train_step": JAX,
    "ddlpc_tpu.train": JAX,
    "ddlpc_tpu.train.__main__": JAX,
    "ddlpc_tpu.train.trainer": JAX,
    "ddlpc_tpu.train.optim": JAX,
    "ddlpc_tpu.train.checkpoint": JAX,
    "ddlpc_tpu.train.async_checkpoint": JAX,
    "ddlpc_tpu.train.observability": JAX,
    "ddlpc_tpu.train.watchdog": JAX,
    "ddlpc_tpu.predict": JAX,
}

_STDLIB_NAMES = frozenset(sys.stdlib_module_names) | {"__future__"}


def discover_modules(pkg_dir: str) -> Dict[str, str]:
    """``ddlpc_tpu.x.y`` module name -> file path under ``pkg_dir``."""
    out: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            parts = rel[:-3].split(os.sep)
            if parts[-1] == "__init__":
                parts = parts[:-1]
            out[".".join(parts)] = path
    return out


def _toplevel_imports(
    tree: ast.Module, module: str, is_pkg: bool
) -> List[Tuple[str, int]]:
    """(imported module name, lineno) for every module-level import.

    ``if TYPE_CHECKING:`` blocks never execute — skipped.  ``try:`` /
    ``if:`` bodies at module level DO execute — included.
    """
    out: List[Tuple[str, int]] = []

    def visit_body(body) -> None:
        for node in body:
            if isinstance(node, ast.Import):
                out.extend((a.name, node.lineno) for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = module.split(".")
                    if not is_pkg:
                        base = base[:-1]
                    base = base[: len(base) - (node.level - 1)]
                    prefix = ".".join(base)
                    mod = (
                        f"{prefix}.{node.module}" if node.module else prefix
                    )
                else:
                    mod = node.module or ""
                if mod:
                    out.append((mod, node.lineno))
                    # `from pkg import name` may bind a SUBMODULE: record
                    # the candidate; the resolver keeps it only if it
                    # exists as a module.
                    for a in node.names:
                        if a.name != "*":
                            out.append((f"{mod}.{a.name}", node.lineno))
            elif isinstance(node, ast.If):
                test = node.test
                is_type_checking = (
                    isinstance(test, ast.Name)
                    and test.id == "TYPE_CHECKING"
                ) or (
                    isinstance(test, ast.Attribute)
                    and test.attr == "TYPE_CHECKING"
                )
                if not is_type_checking:
                    visit_body(node.body)
                visit_body(node.orelse)
            elif isinstance(node, ast.Try):
                visit_body(node.body)
                for h in node.handlers:
                    visit_body(h.body)
                visit_body(node.orelse)
                visit_body(node.finalbody)

    visit_body(tree.body)
    return out


class ImportGraph:
    """Module-level import edges for one source tree."""

    def __init__(self, modules: Dict[str, str]):
        self.modules = modules
        # module -> list of (ddlpc dep, lineno)
        self.internal: Dict[str, List[Tuple[str, int]]] = {}
        # module -> list of (external root, lineno)
        self.external: Dict[str, List[Tuple[str, int]]] = {}
        for name, path in modules.items():
            with open(path, "r", encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue  # the AST rules report syntax errors
            is_pkg = os.path.basename(path) == "__init__.py"
            ints: List[Tuple[str, int]] = []
            exts: List[Tuple[str, int]] = []
            # implicit parent-package edges: importing a.b.c runs a and
            # a.b first
            parent = name.rsplit(".", 1)[0]
            if parent != name:
                ints.append((parent, 0))
            for mod, lineno in _toplevel_imports(tree, name, is_pkg):
                root = mod.split(".")[0]
                if root == "ddlpc_tpu":
                    target = mod
                    while target and target not in modules:
                        target = target.rsplit(".", 1)[0] if "." in target else ""
                    if target and target != name:
                        ints.append((target, lineno))
                else:
                    exts.append((root, lineno))
            self.internal[name] = ints
            self.external[name] = exts

    def reach(
        self, start: str, forbidden
    ) -> Optional[Tuple[List[str], str, int]]:
        """BFS: can ``start`` reach a forbidden external root at import
        time?  Returns (module chain, root, lineno) or None."""
        seen = {start}
        queue: List[Tuple[str, List[str]]] = [(start, [start])]
        while queue:
            mod, path = queue.pop(0)
            for root, lineno in self.external.get(mod, ()):
                if forbidden(root):
                    return path, root, lineno
            for dep, _ in self.internal.get(mod, ()):
                if dep not in seen:
                    seen.add(dep)
                    queue.append((dep, path + [dep]))
        return None


def check_tiers(
    pkg_dir: str, registry: Optional[Dict[str, str]] = None
) -> List[Tuple[str, str, int, str]]:
    """All tier violations for the package at ``pkg_dir``.

    Returns ``(rule_id, path, line, message)`` tuples; empty means every
    declaration is proven.
    """
    registry = MODULE_TIERS if registry is None else registry
    modules = discover_modules(pkg_dir)
    out: List[Tuple[str, str, int, str]] = []
    for name in sorted(set(modules) - set(registry)):
        out.append(
            (
                "tier-undeclared",
                modules[name],
                1,
                f"module {name} is not declared in "
                f"analysis/tiers.py:MODULE_TIERS — new modules must opt "
                f"into a tier explicitly",
            )
        )
    for name in sorted(set(registry) - set(modules)):
        out.append(
            (
                "tier-undeclared",
                os.path.join(pkg_dir, "__init__.py"),
                1,
                f"MODULE_TIERS declares {name} but no such module exists "
                f"— remove the stale entry",
            )
        )
    graph = ImportGraph(modules)

    def forbidden_for(tier: str):
        if tier == JAX:
            return lambda root: False
        if tier == HOST:
            return lambda root: root in JAX_ROOTS
        return lambda root: root not in _STDLIB_NAMES

    for name in sorted(set(modules) & set(registry)):
        tier = registry[name]
        hit = graph.reach(name, forbidden_for(tier))
        if hit is not None:
            chain, root, lineno = hit
            offender = chain[-1]
            out.append(
                (
                    "import-tier",
                    graph.modules[offender],
                    lineno,
                    f"{name} is tier '{tier}' but reaches "
                    f"'import {root}' via {' -> '.join(chain)} "
                    f"(module-level import in {offender})",
                )
            )
        # A declared tier must also bound the declared tiers of direct
        # ddlpc deps — catches a stdlib module leaning on a host module
        # even before the host module grows a forbidden external.
        for dep, lineno in graph.internal.get(name, ()):
            dep_tier = registry.get(dep)
            if dep_tier is not None and _RANK[dep_tier] > _RANK[tier]:
                out.append(
                    (
                        "import-tier",
                        graph.modules[name],
                        lineno or 1,
                        f"{name} (tier '{tier}') imports {dep} "
                        f"(tier '{dep_tier}') at module level — a module "
                        f"may only import its own tier or below",
                    )
                )
    return out
