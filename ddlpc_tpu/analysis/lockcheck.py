"""Lock-order race detector: instrumented locks + ``# guarded-by:`` checks.

Two dynamic invariants, enforced while real threaded code runs (the
existing MicroBatcher / Tracer / StageTimer / router tests, or the bounded
smoke in :mod:`lock_fixtures`):

1. **Lock order** — every acquisition taken while other instrumented locks
   are held records an edge ``held → acquired`` (by lock *name*, so two
   MicroBatcher instances share a node).  A cycle in that graph is a
   lock-order inversion: two threads CAN deadlock even if this run did
   not.  ``cycles()`` finds them; ``scripts/ddlpc_check.py`` fails on any.

2. **Guarded attributes** — classes decorated with :func:`guarded` may
   annotate attribute assignments ``self._q = deque()  # guarded-by:
   _cond``.  While enabled, any post-``__init__`` rebind of an annotated
   attribute — or any mutation of an annotated dict/list/deque through the
   installed proxy — without the named lock held by the current thread is
   recorded as a violation.  ``# guarded-by: <owner-thread>`` instead pins
   the attribute to one mutating thread (single-writer hand-off designs
   like AsyncCheckpointer, where the barrier — not a lock — is the fence).

Cost when disabled (the default): the factories return plain ``threading``
primitives, and :func:`guarded`'s injected ``__setattr__`` is one global
flag test — no source inspection, no proxies, no graph.  Enable with
``DDLPC_LOCKCHECK=1`` in the environment (before the instrumented classes
are *instantiated*) or :func:`enable` in tests.
"""

from __future__ import annotations

import collections as _collections
import os
import re
import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "enable",
    "disable",
    "enabled",
    "reset",
    "lock",
    "rlock",
    "condition",
    "guarded",
    "OWNER_THREAD",
    "edges",
    "cycles",
    "guard_violations",
    "violations",
    "report",
]

OWNER_THREAD = "<owner-thread>"

_enabled = os.environ.get("DDLPC_LOCKCHECK", "") not in ("", "0")

# Global acquisition-graph + violation state, guarded by _STATE_LOCK
# (a plain threading.Lock — the detector must not instrument itself).
_STATE_LOCK = threading.Lock()
_edges: Dict[Tuple[str, str], str] = {}  # (held_name, acquired_name) -> site
_guard_violations: List[str] = []
_owner_threads: Dict[Tuple[int, str], int] = {}  # (id(obj), attr) -> tid
_tls = threading.local()


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn instrumentation on (construct instrumented objects AFTER)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop all recorded edges/violations (test isolation)."""
    with _STATE_LOCK:
        _edges.clear()
        _guard_violations.clear()
        _owner_threads.clear()


def _held() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def _site() -> str:
    # The caller of acquire(): skip this helper, _note_acquire, and the
    # acquire wrapper itself.
    for frame in reversed(traceback.extract_stack(limit=8)[:-3]):
        if os.path.basename(frame.filename) != "lockcheck.py":
            return f"{frame.filename}:{frame.lineno}"
    return "?"


def _note_acquire(lk: "_InstrumentedBase") -> None:
    st = _held()
    first = all(h is not lk for h in st)
    if first:
        new_pairs = [
            (h.name, lk.name)
            for h in st
            if h.name != lk.name and (h.name, lk.name) not in _edges
        ]
        if new_pairs:
            site = _site()
            with _STATE_LOCK:
                for pair in new_pairs:
                    _edges.setdefault(pair, site)
    st.append(lk)


def _note_release(lk: "_InstrumentedBase") -> None:
    st = _held()
    for i in range(len(st) - 1, -1, -1):
        if st[i] is lk:
            del st[i]
            return


class _InstrumentedBase:
    """Common acquire/release bookkeeping over an inner primitive."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquire(self)
        return got

    def release(self) -> None:
        self._inner.release()
        _note_release(self)

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked_by_current_thread(self) -> bool:
        return any(h is self for h in _held())

    # threading.Condition protocol --------------------------------------
    def _is_owned(self) -> bool:
        return self.locked_by_current_thread()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class InstrumentedLock(_InstrumentedBase):
    def __init__(self, name: str):
        super().__init__(name, threading.Lock())


class InstrumentedRLock(_InstrumentedBase):
    def __init__(self, name: str):
        super().__init__(name, threading.RLock())

    # Condition.wait() fully releases a reentrant lock and restores its
    # depth afterwards; mirror that in the held stack so attribute checks
    # during the wait correctly see the lock NOT held.
    def _release_save(self):
        state = self._inner._release_save()
        st = _held()
        _tls_count = sum(1 for h in st if h is self)
        for i in range(len(st) - 1, -1, -1):
            if st[i] is self:
                del st[i]
        return (state, _tls_count)

    def _acquire_restore(self, saved) -> None:
        state, count = saved
        self._inner._acquire_restore(state)
        st = _held()
        st.extend([self] * max(count, 1))

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def lock(name: str):
    """A ``threading.Lock`` — instrumented when lockcheck is enabled."""
    return InstrumentedLock(name) if _enabled else threading.Lock()


def rlock(name: str):
    return InstrumentedRLock(name) if _enabled else threading.RLock()


def condition(name: Optional[str] = None, lock=None):
    """A ``threading.Condition`` over an instrumented (R)Lock.

    Pass ``lock=`` to share an existing (instrumented) lock — the
    FleetRouter's ``_drain_cond`` waits on the router lock itself."""
    if lock is not None:
        return threading.Condition(lock)
    if not _enabled:
        return threading.Condition()
    return threading.Condition(InstrumentedRLock(name or "condition"))


# -- guarded attributes ------------------------------------------------------

_GUARD_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=#\n]+)?=[^#\n]*#\s*guarded-by:\s*([\w<>-]+)"
)


def _guard_map(cls) -> Dict[str, str]:
    gm = cls.__dict__.get("_lc_guard_map")
    if gm is None:
        import inspect

        try:
            src = inspect.getsource(cls)
        except (OSError, TypeError):  # frozen/interactive: nothing to parse
            src = ""
        gm = {m.group(1): m.group(2) for m in _GUARD_RE.finditer(src)}
        cls._lc_guard_map = gm
    return gm


def _lock_of(obj, lockname: str):
    lk = getattr(obj, lockname, None)
    if isinstance(lk, threading.Condition):
        lk = lk._lock
    return lk if isinstance(lk, _InstrumentedBase) else None


def _record_guard_violation(msg: str) -> None:
    with _STATE_LOCK:
        if len(_guard_violations) < 200:  # bounded: a hot loop can't OOM us
            _guard_violations.append(msg)


def _check_guard(obj, attr: str, lockname: str, via: str) -> None:
    if lockname == OWNER_THREAD:
        tid = threading.get_ident()
        key = (id(obj), attr)
        with _STATE_LOCK:
            owner = _owner_threads.setdefault(key, tid)
        if owner != tid:
            _record_guard_violation(
                f"{type(obj).__name__}.{attr} {via} from thread "
                f"{threading.current_thread().name!r} but is owner-thread "
                f"confined (first mutated on tid {owner}) [{_site()}]"
            )
        return
    lk = _lock_of(obj, lockname)
    if lk is None:
        return  # lock not built yet, or not instrumented — nothing to prove
    if not lk._is_owned():
        _record_guard_violation(
            f"{type(obj).__name__}.{attr} {via} without {lockname} "
            f"({lk.name}) held [thread {threading.current_thread().name!r}, "
            f"{_site()}]"
        )


class _GuardedMutator:
    """Mixin: container ops that mutate check the guard first."""

    def _lc_bind(self, owner, attr: str, lockname: str):
        self._lc_owner = owner
        self._lc_attr = attr
        self._lc_lockname = lockname
        return self

    def _lc_check(self) -> None:
        owner = getattr(self, "_lc_owner", None)
        if owner is not None and _enabled and getattr(
            owner, "_lc_init_done", False
        ):
            _check_guard(owner, self._lc_attr, self._lc_lockname, "mutated")


class GuardedDict(dict, _GuardedMutator):
    pass


class GuardedList(list, _GuardedMutator):
    pass


class GuardedDeque(_collections.deque, _GuardedMutator):
    pass


def _install_mutators(cls, base, names) -> None:
    for name in names:
        base_fn = getattr(base, name)

        def op(self, *a, _fn=base_fn, **kw):
            self._lc_check()
            return _fn(self, *a, **kw)

        op.__name__ = name
        setattr(cls, name, op)


_install_mutators(
    GuardedDict, dict,
    ("__setitem__", "__delitem__", "pop", "popitem", "clear",
     "setdefault", "update"),
)
_install_mutators(
    GuardedList, list,
    ("__setitem__", "__delitem__", "append", "extend", "insert",
     "pop", "remove", "clear", "sort"),
)
_install_mutators(
    GuardedDeque, _collections.deque,
    ("__setitem__", "__delitem__", "append", "appendleft", "extend",
     "extendleft", "pop", "popleft", "remove", "clear"),
)

def _wrap_container(value, owner, attr: str, lockname: str):
    """Annotated dict/list/deque → checking proxy (exact types only; an
    already-wrapped or exotic container passes through unwrapped)."""
    t = type(value)
    if t is dict:
        return GuardedDict(value)._lc_bind(owner, attr, lockname)
    if t is list:
        return GuardedList(value)._lc_bind(owner, attr, lockname)
    if t is _collections.deque:
        # preserve maxlen — a bounded ring must stay bounded under check
        return GuardedDeque(value, value.maxlen)._lc_bind(
            owner, attr, lockname
        )
    return value


def guarded(cls):
    """Class decorator enforcing the class's ``# guarded-by:`` comments.

    Disabled: the injected ``__setattr__`` is one flag test on top of
    ``object.__setattr__`` (these classes assign attributes at
    construction and on cold paths, not per-item).  Enabled: annotated
    attribute rebinds are checked against the named lock, and annotated
    dict/list/deque values are replaced with checking proxies so item-level
    mutation (``self.totals[k] = ...``, ``self._q.popleft()``) is checked
    too.  ``__init__`` runs unchecked (single-threaded construction), like
    every guarded-by system's constructor exemption.
    """
    orig_init = cls.__init__
    orig_setattr = cls.__setattr__

    def __init__(self, *a, **kw):
        orig_init(self, *a, **kw)
        object.__setattr__(self, "_lc_init_done", True)

    def __setattr__(self, name, value):
        if _enabled:
            gm = _guard_map(type(self))
            lockname = gm.get(name)
            if lockname is not None:
                if lockname != OWNER_THREAD:
                    value = _wrap_container(value, self, name, lockname)
                if getattr(self, "_lc_init_done", False):
                    _check_guard(self, name, lockname, "rebound")
        orig_setattr(self, name, value)

    cls.__init__ = __init__
    cls.__setattr__ = __setattr__
    return cls


# -- reporting ---------------------------------------------------------------

def edges() -> Dict[Tuple[str, str], str]:
    with _STATE_LOCK:
        return dict(_edges)


def cycles() -> List[List[str]]:
    """Elementary cycles in the acquisition graph (lock-order inversions).

    Names are canonicalized so each cycle is reported once.  The graph is
    tiny (one node per lock *name*), so a DFS per node is plenty.
    """
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges():
        graph.setdefault(a, []).append(b)
    found: List[List[str]] = []
    seen_keys = set()

    def dfs(start: str, node: str, path: List[str], visited: set) -> None:
        for nxt in graph.get(node, ()):
            if nxt == start:
                cyc = path[:]
                i = cyc.index(min(cyc))
                key = tuple(cyc[i:] + cyc[:i])
                if key not in seen_keys:
                    seen_keys.add(key)
                    found.append(list(key))
            elif nxt not in visited and nxt > start:
                # only explore names > start: each cycle found from its
                # smallest node exactly once
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return found


def guard_violations() -> List[str]:
    with _STATE_LOCK:
        return list(_guard_violations)


def violations() -> List[str]:
    """Human-readable lock-order + guarded-by violations (empty = clean)."""
    out = []
    es = edges()
    for cyc in cycles():
        hops = []
        ring = cyc + [cyc[0]]
        for a, b in zip(ring, ring[1:]):
            hops.append(f"{a} -> {b} [{es.get((a, b), '?')}]")
        out.append("lock-order inversion: " + "; ".join(hops))
    out.extend(f"guarded-by: {v}" for v in guard_violations())
    return out


def report() -> dict:
    """Flat-ish summary for the analyzer's ``analysis`` record stream."""
    return {
        "edges": [f"{a} -> {b}" for (a, b) in sorted(edges())],
        "cycles": [" -> ".join(c) for c in cycles()],
        "guard_violations": guard_violations(),
    }
