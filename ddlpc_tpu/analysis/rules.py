"""The project AST rules (catalog + rationale: docs/ANALYSIS.md).

Each rule is one class; all of them run off one shared AST walk
(:mod:`core`).  Rules are deliberately *syntactic* — they prove the
idioms the repo's contracts are written in, not arbitrary data flow — and
every escape hatch is an inline suppression with a written reason, so the
exemption ships in the same diff as the code it excuses.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ddlpc_tpu.analysis.core import FileContext, Rule, Violation


def _call_name(node: ast.AST) -> str:
    """Dotted name of a call target: ``json.dumps`` / ``open`` / ``fq``."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def _contains_call(node: ast.AST, dotted: str) -> bool:
    return any(
        isinstance(n, ast.Call) and _call_name(n.func) == dotted
        for n in ast.walk(node)
    )


def _is_json_dumps(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _call_name(node.func) in (
        "json.dumps",
        "dumps",
    )


# Rules accumulate into a per-rule list surfaced via finalize(); keep the
# plumbing in one place.
def ctx_violations(rule: Rule, ctx: FileContext) -> List[Violation]:
    store = getattr(rule, "_violations", None)
    if store is None:
        store = rule._violations = []
    return store


class _CollectingRule(Rule):
    def finalize(self, root: str) -> List[Violation]:
        out = getattr(self, "_violations", [])
        self._violations = []
        return out


class JsonlStampRule(_CollectingRule):
    """jsonl-stamp: a ``f.write(json.dumps(rec) + "\\n")`` emit site must
    stamp the record (``obs.schema.stamp``, an explicit ``"schema"`` key,
    or ``setdefault("schema", ...)`` in the same function).  Pass-throughs
    that re-emit decoded lines (``json.loads`` inside the dumped
    expression) are exempt — the stamp rode in on the original record."""

    id = "jsonl-stamp"
    doc = (
        "JSONL emit sites must flow through a schema-stamping helper "
        "(obs/schema.py:stamp) so every stream lints clean"
    )

    def visit_Call(self, node: ast.Call, ctx: FileContext) -> None:
        # shape: <something>.write( json.dumps(...) [+ "\n"] )
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "write"
            and len(node.args) == 1
        ):
            return
        arg = node.args[0]
        dumped: Optional[ast.Call] = None
        if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
            if _is_json_dumps(arg.left):
                dumped = arg.left
        elif _is_json_dumps(arg):
            dumped = arg
        if dumped is None:
            return
        if any(
            kw.arg == "indent" for kw in dumped.keywords
        ):
            return  # pretty-printed report JSON, not a JSONL stream
        if any(_is_json_loads(n) for n in ast.walk(dumped)):
            return  # pass-through of an already-stamped record
        func = ctx.enclosing_function(node)
        scope = func if func is not None else ctx.tree
        if _has_stamp_evidence(scope):
            return
        ctx_violations(self, ctx).append(
            Violation(
                self.id, ctx.path, node.lineno,
                "JSONL record written without schema stamping — build the "
                "record via obs.schema.stamp(...) (or set 'schema' "
                "explicitly in this function)",
            )
        )


def _is_json_loads(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _call_name(node.func) in (
        "json.loads",
        "loads",
    )


def _has_stamp_evidence(scope: ast.AST) -> bool:
    for n in ast.walk(scope):
        if isinstance(n, ast.Call):
            name = _call_name(n.func)
            if name in ("stamp", "schema.stamp") or name.endswith(".stamp"):
                return True
            if (
                isinstance(n.func, ast.Attribute)
                and n.func.attr == "setdefault"
                and n.args
                and isinstance(n.args[0], ast.Constant)
                and n.args[0].value == "schema"
            ):
                return True
        if isinstance(n, ast.Dict):
            for k in n.keys:
                if isinstance(k, ast.Constant) and k.value == "schema":
                    return True
        if (
            isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Subscript)
        ):
            s = n.targets[0].slice
            if isinstance(s, ast.Constant) and s.value == "schema":
                return True
    return False


class AtomicWriteRule(_CollectingRule):
    """atomic-write: report/metadata JSONs go to disk via
    tmp + fsync + rename (``utils.fsio.atomic_write_json`` or a function
    that performs ``os.replace`` + ``os.fsync`` itself), never a bare
    ``open(path, "w")`` — a crash mid-write must not leave a torn file
    where a committed artifact or a restore path expects a whole one."""

    id = "atomic-write"
    doc = (
        "JSON report writes use the tmp+rename helpers (utils/fsio.py), "
        "never bare open(..., 'w')"
    )

    def _function_is_atomic(self, scope: ast.AST) -> bool:
        # ``os.replace`` in the same function marks a self-rolled atomic
        # writer: rename-atomicity (no torn reads) is the invariant this
        # rule proves.  fsync is a separate DURABILITY decision the
        # helpers own per call site (fsio.atomic_write_* ``durable=`` —
        # ~50 ms per fsync on containerized filesystems, so per-epoch
        # writers opt out explicitly).
        return any(
            isinstance(n, ast.Call)
            and _call_name(n.func) in ("os.replace", "os.rename", "replace")
            for n in ast.walk(scope)
        )

    def _open_w_names(self, scope: ast.AST) -> Dict[str, int]:
        """Names bound to a bare ``open(..., 'w'/'wb')`` in this scope
        (with-items and assignments)."""
        names: Dict[str, int] = {}

        def mode_of(call: ast.Call) -> str:
            if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
                return str(call.args[1].value)
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    return str(kw.value.value)
            return "r"

        for n in ast.walk(scope):
            call = None
            target = None
            if isinstance(n, ast.withitem) and isinstance(
                n.context_expr, ast.Call
            ):
                call, target = n.context_expr, n.optional_vars
            elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                call = n.value
                if len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    target = n.targets[0]
            if (
                call is not None
                and _call_name(call.func) == "open"
                and "w" in mode_of(call)
                and isinstance(target, ast.Name)
            ):
                names[target.id] = call.lineno
        return names

    def visit_Call(self, node: ast.Call, ctx: FileContext) -> None:
        func = ctx.enclosing_function(node)
        scope = func if func is not None else ctx.tree
        # json.dump(obj, f) where f came from a bare open(..., 'w')
        hit_line = None
        if _call_name(node.func) in ("json.dump", "dump"):
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Name):
                opens = self._open_w_names(scope)
                if node.args[1].id in opens:
                    hit_line = node.lineno
        # f.write(json.dumps(...)) / f.write(name_bound_to_dumps)
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "write"
            and isinstance(node.func.value, ast.Name)
            and len(node.args) == 1
        ):
            opens = self._open_w_names(scope)
            if node.func.value.id in opens:
                arg = node.args[0]
                dumped = any(_is_json_dumps(n) for n in ast.walk(arg))
                if not dumped:
                    # names in the written expression bound from
                    # json.dumps earlier in the scope (`out = dumps(...);
                    # f.write(out + "\n")`)
                    arg_names = {
                        n.id
                        for n in ast.walk(arg)
                        if isinstance(n, ast.Name)
                    }
                    dumped = any(
                        isinstance(n, ast.Assign)
                        and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)
                        and n.targets[0].id in arg_names
                        and any(
                            _is_json_dumps(m) for m in ast.walk(n.value)
                        )
                        for n in ast.walk(scope)
                    )
                if dumped:
                    hit_line = node.lineno
        if hit_line is None:
            return
        if self._function_is_atomic(scope):
            return  # this IS an atomic writer (tmp + fsync + rename)
        ctx_violations(self, ctx).append(
            Violation(
                self.id, ctx.path, hit_line,
                "JSON written through a bare open(..., 'w') — use "
                "ddlpc_tpu.utils.fsio.atomic_write_json (tmp + fsync + "
                "rename) so a crash cannot leave a torn report",
            )
        )


class MetricDocRule(_CollectingRule):
    """metric-doc: every constant ``ddlpc_*`` metric name in code appears
    in docs/OBSERVABILITY.md, and every full metric name in the doc's
    tables exists in code — drift fails in BOTH directions.  Doc names on
    lines marked ``(dynamic)`` (or containing ``<key>`` templates) are
    derived at runtime and exempt from the code-presence direction."""

    id = "metric-doc"
    doc = (
        "ddlpc_* metric names in code and docs/OBSERVABILITY.md must "
        "match exactly, both directions"
    )

    DOC = os.path.join("docs", "OBSERVABILITY.md")
    _NAME = re.compile(r"^ddlpc_[a-z0-9_]*[a-z0-9]$")
    _DOC_TOKEN = re.compile(r"ddlpc_[a-z0-9_<>]*")
    # names that are identifiers, not metrics, when they appear in prose
    NON_METRIC = frozenset({"ddlpc_tpu", "ddlpc_check"})

    def __init__(self):
        self._code_names: Dict[str, Tuple[str, int]] = {}

    def visit_Constant(self, node: ast.Constant, ctx: FileContext) -> None:
        v = node.value
        if (
            isinstance(v, str)
            and self._NAME.match(v)
            and v not in self.NON_METRIC
        ):
            self._code_names.setdefault(v, (ctx.path, node.lineno))

    def finalize(self, root: str) -> List[Violation]:
        out = list(getattr(self, "_violations", []))
        self._violations = []
        doc_path = os.path.join(root, self.DOC)
        if not os.path.exists(doc_path):
            return out  # mini fixture trees without docs skip this rule
        doc_names: Set[str] = set()
        dynamic_prefixes: Set[str] = set()
        with open(doc_path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                for tok in self._DOC_TOKEN.findall(line):
                    if "<" in tok or tok.endswith("_"):
                        prefix = tok.split("<")[0]
                        # the bare family prefix would exempt EVERYTHING;
                        # a dynamic prefix must name an actual subfamily
                        if len(prefix) > len("ddlpc_"):
                            dynamic_prefixes.add(prefix)
                    elif self._NAME.match(tok) and tok not in self.NON_METRIC:
                        if "(dynamic)" in line:
                            dynamic_prefixes.add(tok)
                        else:
                            doc_names.add(tok)
        for name, (path, lineno) in sorted(self._code_names.items()):
            if name not in doc_names:
                out.append(
                    Violation(
                        self.id, path, lineno,
                        f"metric {name!r} is emitted here but missing from "
                        f"{self.DOC} — document it (or it silently "
                        f"disappears from the operator's map)",
                    )
                )
        for name in sorted(doc_names - set(self._code_names)):
            if any(name.startswith(p) for p in dynamic_prefixes):
                continue
            out.append(
                Violation(
                    self.id, doc_path, 1,
                    f"{self.DOC} documents {name!r} but no code emits it — "
                    f"stale docs mislead operators; delete the row or mark "
                    f"the line (dynamic)",
                )
            )
        self._code_names = {}
        return out


class JitHostCallRule(_CollectingRule):
    """jit-host-call: functions compiled by ``jit``/``pmap``/``shard_map``
    must not call host-side APIs — ``time.*`` clocks, ``.item()``,
    ``device_get``, or numpy functions.  Each of these either recompiles
    per call, forces an implicit device→host transfer, or silently bakes a
    trace-time constant into the compiled program."""

    id = "jit-host-call"
    doc = (
        "no time.time()/.item()/device_get/numpy host calls inside "
        "functions passed to jit/shard_map/pmap"
    )

    _WRAPPERS = {"jit", "pmap", "shard_map"}
    _NP_OK = frozenset(
        {
            "float32", "float16", "bfloat16", "int32", "int8", "int64",
            "uint8", "uint16", "bool_", "float64", "dtype", "pi", "inf",
            "newaxis",
        }
    )

    def begin_file(self, ctx: FileContext) -> None:
        self._jitted: List[Tuple[ast.AST, str]] = []
        self._defs: Dict[str, ast.AST] = {}

    def _is_wrapper(self, func: ast.AST) -> bool:
        name = _call_name(func)
        return bool(name) and name.split(".")[-1] in self._WRAPPERS

    def visit_FunctionDef(self, node: ast.FunctionDef, ctx: FileContext):
        self._defs[node.name] = node
        for dec in node.decorator_list:
            jitted = self._is_wrapper(dec) or (
                isinstance(dec, ast.Call)
                and (
                    self._is_wrapper(dec.func)
                    or (
                        _call_name(dec.func).split(".")[-1] == "partial"
                        and dec.args
                        and self._is_wrapper(dec.args[0])
                    )
                )
            )
            if jitted:
                self._jitted.append((node, node.name))

    def visit_Call(self, node: ast.Call, ctx: FileContext) -> None:
        if not self._is_wrapper(node.func) or not node.args:
            return
        target = node.args[0]
        if isinstance(target, ast.Lambda):
            self._jitted.append((target, "<lambda>"))
        elif isinstance(target, ast.Name):
            self._jitted.append((target, target.id))  # resolved at finalize

    def _scan(self, fn: ast.AST, label: str, ctx: FileContext) -> None:
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n.func)
            msg = None
            if name in ("time.time", "time.monotonic", "time.perf_counter"):
                msg = f"{name}() is a trace-time constant under jit"
            elif (
                isinstance(n.func, ast.Attribute)
                and n.func.attr == "item"
                and not n.args
            ):
                msg = ".item() forces a device->host sync inside the " \
                      "compiled function"
            elif name.split(".")[-1] == "device_get":
                msg = "device_get inside a jitted function is an implicit " \
                      "transfer"
            elif name.split(".")[0] in ("np", "numpy"):
                attr = name.split(".")[-1]
                if attr not in self._NP_OK:
                    msg = (
                        f"numpy host call {name}() inside a jitted "
                        f"function runs at trace time, not per step"
                    )
            if msg is not None:
                ctx_violations(self, ctx).append(
                    Violation(
                        self.id, ctx.path, n.lineno,
                        f"in jit-compiled {label!r}: {msg}",
                    )
                )

    def end_file(self, ctx: FileContext) -> None:
        # resolve Name targets recorded during the walk, then scan
        seen: Set[int] = set()
        for target, label in self._jitted:
            fn = target
            if isinstance(target, ast.Name):
                fn = self._defs.get(label)
                if fn is None:
                    continue
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            self._scan(fn, label, ctx)
        self._jitted = []
        self._defs = {}


class CodecFenceRule(_CollectingRule):
    """codec-fence: inside ``parallel/``, the quantization codec runs only
    through ``grad_sync.apply_codec_fenced`` (or inside a function that
    cuts its own ``optimization_barrier`` fences at the same points).
    An unfenced codec call fuses into the surrounding collectives and its
    bits then depend on which program surrounds it — the exact 1-ulp
    drift PR 5's bit-identity bar exists to prevent."""

    id = "codec-fence"
    doc = (
        "codec invocations in parallel/ go through apply_codec_fenced "
        "(PR 5 bit-identity fences)"
    )

    _CODEC_FNS = {"fake_quantize", "fake_quantize_pallas", "fq"}

    def visit_Call(self, node: ast.Call, ctx: FileContext) -> None:
        if os.sep + "parallel" + os.sep not in ctx.path:
            return
        name = _call_name(node.func)
        if name not in self._CODEC_FNS:
            return
        func = ctx.enclosing_function(node)
        if func is not None and func.name == "apply_codec_fenced":
            return  # the wrapper itself
        if func is not None and _contains_call(
            func, "lax.optimization_barrier"
        ):
            return  # function cuts its own fences (ring inline formula)
        ctx_violations(self, ctx).append(
            Violation(
                self.id, ctx.path, node.lineno,
                f"unfenced codec call {name}(...) in parallel/ — route "
                f"through grad_sync.apply_codec_fenced so the codec's "
                f"bits cannot depend on the surrounding program",
            )
        )


def make_rules() -> List[Rule]:
    return [
        JsonlStampRule(),
        AtomicWriteRule(),
        MetricDocRule(),
        JitHostCallRule(),
        CodecFenceRule(),
    ]


ALL_RULE_IDS = [r.id for r in make_rules()] + [
    "import-tier",
    "tier-undeclared",
    "lock-order",
    "guarded-by",
    "bad-suppression",
    "syntax-error",
]
